"""The replicated counter machine shared by the cluster-shaped benches
and the deployment plane's child processes (docs/DEPLOYMENT.md).

Lives OUTSIDE ``bench.py`` on purpose: a deployed member/ingress process
(``python -m copycat_tpu.deploy.child``) imports this module by machine
spec (``copycat_tpu.testing.counter_machine:counter_machine``) to host
the workload the compartment bench drives — importing ``bench.py`` for
the class would drag jax and the engine stack into every child, and the
serialization ids (940/941) must bind to exactly ONE class each, so the
bench and the children must share this definition.

Import of this module registers the op types with the serializer — any
process that decodes ``ClusterAdd`` frames (members, ingress proxies,
clients) must import it before the first frame arrives; the machine
spec on the topology does that for spawned children.
"""

from __future__ import annotations

import zlib

from ..io.serializer import serialize_with
from ..protocol.messages import Message
from ..protocol.operations import Command, Query
from ..server.state_machine import Commit, StateMachine


@serialize_with(940)
class ClusterAdd(Message, Command):
    _fields = ("key", "delta")


@serialize_with(941)
class ClusterGet(Message, Query):
    _fields = ("key",)


class CounterMachine(StateMachine):
    """Keyed counters: ``ClusterAdd`` increments, ``ClusterGet`` reads."""

    def __init__(self) -> None:
        super().__init__()
        self.data: dict = {}

    # explicit registration: the auto-register table resolves
    # annotations in module scope, and Commit is only imported here
    def configure(self, executor) -> None:
        executor.register(ClusterAdd, self.add)
        executor.register(ClusterGet, self.get)

    def add(self, commit: "Commit") -> int:
        op = commit.operation
        value = self.data.get(op.key, 0) + op.delta
        self.data[op.key] = value
        return value

    def get(self, commit: "Commit") -> int:
        return self.data.get(commit.operation.key, 0)

    # crash-recovery plane hooks (docs/DURABILITY.md): the recovery
    # scenario snapshots + restores this machine; the cluster
    # scenario's durable storage levels snapshot it too
    def snapshot_state(self):
        return {"data": dict(self.data)}

    def restore_state(self, data, sessions) -> None:
        self.data = dict(data["data"])

    # keyspace sharding (docs/SHARDING.md): counters route across Raft
    # groups by a stable key hash — identical on every member, every
    # ingress proxy, and across restarts
    @classmethod
    def route_group(cls, operation, groups: int) -> int:
        key = getattr(operation, "key", None)
        if isinstance(key, str):
            return zlib.crc32(key.encode()) % groups
        return 0


def counter_machine(group: int = 0) -> CounterMachine:
    """Per-group machine factory (the deployment plane's machine-spec
    entry point: ``copycat_tpu.testing.counter_machine:counter_machine``)."""
    return CounterMachine()
