"""Wing & Gong linearizability checker with sequential resource models.

The reference relies on the external ``atomix-jepsen`` suite for this
(``/root/reference/README.md:27-30``); SURVEY.md §4 names an in-tree
checker as a build obligation. The algorithm is the classic Wing & Gong
search with Lowe's memoization: try every *minimal* pending operation (one
no other op completed before its invocation), advance the sequential model,
and backtrack on result mismatch. Histories record real-time windows
``[invoke, complete]`` in driver rounds; incomplete operations (crashed
clients) may linearize at any point or never.

Models mirror the device kernels' result conventions (``ops/apply.py``)
so recorded raw int results can be checked without translation.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field


@dataclass(frozen=True)
class HOp:
    """One operation in a history."""

    op_id: int
    op: tuple              # model operation, e.g. ("cas", expect, update)
    result: int | None     # raw result; None = unknown (never completed)
    invoke: float          # round at submission
    complete: float = math.inf  # round at completion (inf = incomplete)


class RegisterModel:
    """Linearizable int register (device value/long kernel semantics)."""

    init = 0

    @staticmethod
    def apply(state: int, op: tuple) -> tuple[int, int]:
        name = op[0]
        if name == "set":
            return op[1], 0
        if name == "get":
            return state, state
        if name == "cas":
            if state == op[1]:
                return op[2], 1
            return state, 0
        if name == "gas":
            return op[1], state
        if name == "add":
            return state + op[1], state + op[1]
        raise ValueError(f"unknown register op {name}")


class CounterModel(RegisterModel):
    """Alias — add/get over an int (DistributedAtomicLong semantics)."""


class MapModel:
    """int→int map; state is a hashable frozenset of items."""

    init = frozenset()

    @staticmethod
    def apply(state: frozenset, op: tuple):
        d = dict(state)
        name = op[0]
        if name == "put":
            old = d.get(op[1], 0)
            d[op[1]] = op[2]
            return frozenset(d.items()), old
        if name == "get":
            return state, d.get(op[1], 0)
        if name == "remove":
            old = d.pop(op[1], 0)
            return frozenset(d.items()), old
        if name == "contains":
            return state, int(op[1] in d)
        if name == "size":
            return state, len(d)
        raise ValueError(f"unknown map op {name}")


class LockModel:
    """try-lock/unlock histories (synchronous results only)."""

    init = -1  # holder id, -1 = free

    @staticmethod
    def apply(state: int, op: tuple) -> tuple[int, int]:
        name, who = op[0], op[1]
        if name == "acquire":        # try-lock: immediate grant or fail;
            if state in (-1, who):   # re-acquire by the holder is idempotent
                return who, 1        # (device kernel semantics, apply.py)
            return state, 0
        if name == "release":
            if state == who:
                return -1, 1
            return state, 0
        raise ValueError(f"unknown lock op {name}")


@dataclass
class CheckResult:
    ok: bool
    nodes: int
    witness: list[int] = field(default_factory=list)  # linearization order


def check_linearizable(history: list[HOp], model,
                       max_nodes: int = 2_000_000,
                       init_state=None) -> CheckResult:
    """Return whether ``history`` is linearizable w.r.t. ``model``.

    Raises ``RuntimeError`` if the search exceeds ``max_nodes`` (history too
    concurrent to decide) — never returns a false verdict.
    """
    by_id = {h.op_id: h for h in history}
    ids = frozenset(by_id)
    init = model.init if init_state is None else init_state

    def all_incomplete(remaining: frozenset) -> bool:
        # only incomplete ops left — they may never apply
        return all(by_id[i].complete == math.inf for i in remaining)

    if all_incomplete(ids):
        return CheckResult(ok=True, nodes=0, witness=[])

    memo: set = set()
    nodes = 1
    order: list[int] = []

    def candidates(remaining: frozenset, state):
        min_complete = min(by_id[i].complete for i in remaining)
        for i in sorted(remaining):
            h = by_id[i]
            if h.invoke > min_complete:
                continue  # some other op completed before this was invoked
            new_state, res = model.apply(state, h.op)
            if h.result is not None and res != h.result:
                continue
            yield i, remaining - {i}, new_state

    # Explicit stack (NOT recursion: a linearization is one stack frame
    # per op, and deep verdict histories run thousands of ops — Python's
    # recursion limit turned them into spurious 'undecided' groups).
    # Frame = (remaining, state, candidate iterator, owns_order_slot).
    stack = [(ids, init, candidates(ids, init), False)]
    while stack:
        remaining, state, it, owns = stack[-1]
        advanced = False
        for i, nr, ns in it:
            order.append(i)
            if all_incomplete(nr):
                return CheckResult(ok=True, nodes=nodes,
                                   witness=list(order))
            if (nr, ns) in memo:
                order.pop()
                continue
            nodes += 1
            if nodes > max_nodes:
                raise RuntimeError(
                    f"linearizability search exceeded {max_nodes} nodes")
            stack.append((nr, ns, candidates(nr, ns), True))
            advanced = True
            break
        if not advanced:
            memo.add((remaining, state))
            stack.pop()
            if owns:
                order.pop()
    return CheckResult(ok=False, nodes=nodes, witness=[])


def quiescent_segments(history: list[HOp]) -> list[list[HOp]]:
    """Split a history at quiescent cuts — points strictly after every
    earlier op's completion and strictly before every later op's
    invocation, with no incomplete op before the cut. No operation spans
    a cut, so a linearization of the whole history is exactly a
    concatenation of per-segment linearizations (threading the model
    state through): segment-wise checking is sound AND complete. An
    incomplete op (may linearize at any later point, or never) blocks
    every later cut, keeping the suffix one segment."""
    hs = sorted(history, key=lambda h: (h.invoke, h.op_id))
    segments: list[list[HOp]] = []
    current: list[HOp] = []
    hi = -math.inf  # max completion (inf once an incomplete op is seen)
    for h in hs:
        if current and hi < h.invoke:
            segments.append(current)
            current = []
        current.append(h)
        hi = max(hi, h.complete)
    if current:
        segments.append(current)
    return segments


def check_linearizable_windowed(history: list[HOp], model,
                                max_nodes: int = 2_000_000,
                                init_state=None) -> CheckResult:
    """Segment-wise Wing & Gong over quiescent cuts (same verdict as the
    monolithic search, tractable on long low-concurrency histories —
    search cost becomes ~linear in ops instead of exponential windows
    compounding). ``init_state`` starts the model elsewhere than
    ``model.init`` — used by harnesses that fence a history (e.g. the
    deep verdict anchors post-abort segments on a linearizable read)."""
    nodes_total = 0
    state = model.init if init_state is None else init_state
    for seg in quiescent_segments(history):
        res = check_linearizable(seg, model, max_nodes=max_nodes,
                                 init_state=state)
        nodes_total += res.nodes
        if not res.ok:
            return CheckResult(ok=False, nodes=nodes_total,
                               witness=res.witness)
        by_id = {h.op_id: h for h in seg}
        for op_id in res.witness:  # thread the segment's end state
            state, _ = model.apply(state, by_id[op_id].op)
    return CheckResult(ok=True, nodes=nodes_total, witness=[])


def check_map_linearizable(history: list[HOp],
                           max_nodes: int = 2_000_000) -> CheckResult:
    """Map histories decomposed per key (every verdict map op is
    single-key: ``op[1]``), each key checked as an independent object —
    sound and complete by Herlihy & Wing locality — then windowed."""
    # Decompose ONLY when every op is provably single-key — an allowlist,
    # so a future multi-key op (size, contains_value, ...) routes to the
    # sound monolithic fallback by default instead of silently splitting.
    single_key_ops = ("put", "get", "remove", "contains")
    if any(h.op[0] not in single_key_ops for h in history):
        return check_linearizable_windowed(history, MapModel,
                                           max_nodes=max_nodes)
    by_key: dict = {}
    for h in history:
        by_key.setdefault(h.op[1], []).append(h)
    nodes_total = 0
    for key_hist in by_key.values():
        res = check_linearizable_windowed(key_hist, MapModel,
                                          max_nodes=max_nodes)
        nodes_total += res.nodes
        if not res.ok:
            return CheckResult(ok=False, nodes=nodes_total,
                               witness=res.witness)
    return CheckResult(ok=True, nodes=nodes_total, witness=[])
