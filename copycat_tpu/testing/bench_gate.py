"""CI perf-regression gate over ``bench.py --metrics-json`` artifacts.

The bench trajectory finally has teeth: CI's ``bench-baseline`` job runs
the tiny spi + sharded smokes, then this gate compares each artifact's
headline value against the committed window in
``tests/golden/bench_baseline.json`` — per scenario, ``floor =
baseline x (1 - tolerance)`` (tolerance defaults to 0.25: CI-host
jitter, not a quality bar). Below the floor fails the job and prints
the exact update command; above ``baseline x (1 + tolerance)`` passes
with a "baseline looks stale" note so genuine wins get captured rather
than silently widening the window.

The golden records the value PLUS the artifact's ``meta`` block (git
SHA, knob overrides, host fingerprint — ``bench._artifact_meta``), so a
miss can be explained: a different host or knob set is a different
experiment, not a regression.

Usage (no jax import — artifacts are plain JSON)::

    python -m copycat_tpu.testing.bench_gate A.json B.json
    python -m copycat_tpu.testing.bench_gate A.json --update-golden
"""

from __future__ import annotations

import argparse
import json
import os
import sys

DEFAULT_TOLERANCE = 0.25
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_GOLDEN = os.path.join(_REPO_ROOT, "tests", "golden",
                              "bench_baseline.json")


def load_golden(path: str) -> dict:
    try:
        with open(path) as f:
            golden = json.load(f)
    except FileNotFoundError:
        golden = {}
    golden.setdefault("tolerance", DEFAULT_TOLERANCE)
    golden.setdefault("scenarios", {})
    return golden


def gate_artifact(artifact: dict, golden: dict) -> tuple[bool, str]:
    """Judge one artifact against the golden window; returns
    ``(passed, one-line verdict)``."""
    scenario = artifact.get("scenario", "?")
    value = artifact.get("value")
    unit = artifact.get("unit", "?")
    if not isinstance(value, (int, float)) or value <= 0:
        return False, (f"{scenario}: artifact carries no positive "
                       f"headline value ({value!r})")
    entry = golden["scenarios"].get(scenario)
    if entry is None:
        return False, (f"{scenario}: no committed baseline — record one "
                       f"with --update-golden")
    if entry.get("unit") != unit:
        return False, (f"{scenario}: unit changed "
                       f"({entry.get('unit')!r} -> {unit!r}) — the "
                       f"scenario is measuring something else; "
                       f"--update-golden after reviewing")
    art_degraded = bool(artifact.get("degraded"))
    base_degraded = bool(entry.get("degraded"))
    if art_degraded != base_degraded:
        # A "degraded": true artifact ran on the CPU fallback lane
        # (bench.py: accelerator unreachable) — grading it against a
        # window recorded on the other plane compares two different
        # experiments, so the device-plane floor is SKIPPED, not graded.
        # Honest, visible, and never a silent pass-through: the verdict
        # carries the mismatch so the job log shows which lane ran.
        art_lane = "degraded/CPU-fallback" if art_degraded else \
            "non-degraded"
        base_lane = "degraded/CPU-fallback" if base_degraded else \
            "non-degraded"
        return True, (f"{scenario}: degraded_mismatch — artifact is "
                      f"{art_lane} but the committed window is "
                      f"{base_lane}; device-plane floor skipped (value "
                      f"{value:,.1f} {unit} recorded, not graded). "
                      f"Refresh the window on the matching lane with "
                      f"--update-golden once the lane is stable.")
    tolerance = golden["tolerance"]
    baseline = float(entry["value"])
    floor = baseline * (1.0 - tolerance)
    if value < floor:
        verdict = (f"{scenario}: REGRESSION {value:,.1f} {unit} < "
                   f"floor {floor:,.1f} (baseline {baseline:,.1f} "
                   f"-{tolerance:.0%})")
        rec = (entry.get("recorded") or {}).get("host") or {}
        here = (artifact.get("meta") or {}).get("host") or {}
        probe = ("hostname", "machine", "cpus")
        if rec and here and any(rec.get(k) != here.get(k)
                                for k in probe):
            verdict += (f" — note: baseline was recorded on "
                        f"{rec.get('hostname')}/{rec.get('machine')}/"
                        f"{rec.get('cpus')}cpu, this run is "
                        f"{here.get('hostname')}/{here.get('machine')}/"
                        f"{here.get('cpus')}cpu; a different machine is "
                        f"a different experiment — refresh the baseline "
                        f"on THIS runner before reading this as a "
                        f"regression")
        return False, verdict
    if value > baseline * (1.0 + tolerance):
        return True, (f"{scenario}: ok {value:,.1f} {unit} — ABOVE the "
                      f"+{tolerance:.0%} window (baseline "
                      f"{baseline:,.1f} looks stale; consider "
                      f"--update-golden)")
    return True, (f"{scenario}: ok {value:,.1f} {unit} (baseline "
                  f"{baseline:,.1f}, floor {floor:,.1f})")


def update_golden(artifacts: list[dict], golden: dict) -> dict:
    for artifact in artifacts:
        # only value/unit/meta are recorded — bulky run-local payloads
        # ("metrics" snapshots, retained "series" windows) are tolerated
        # on the artifact but never committed into the golden
        entry = {
            "value": artifact["value"],
            "unit": artifact.get("unit"),
            "recorded": artifact.get("meta", {}),
        }
        if artifact.get("degraded"):
            # record the lane so a later non-degraded run is a
            # degraded_mismatch (skipped), not a spurious "win"
            entry["degraded"] = True
        golden["scenarios"][artifact["scenario"]] = entry
    return golden


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m copycat_tpu.testing.bench_gate",
        description="compare bench --metrics-json artifacts against the "
                    "committed bench_baseline.json window")
    parser.add_argument("artifacts", nargs="+", metavar="ARTIFACT.json")
    parser.add_argument("--golden", default=DEFAULT_GOLDEN,
                        help="baseline file (default: "
                             "tests/golden/bench_baseline.json)")
    parser.add_argument("--update-golden", action="store_true",
                        help="rewrite the baseline entries from these "
                             "artifacts instead of gating")
    args = parser.parse_args(argv)

    artifacts = []
    for path in args.artifacts:
        with open(path) as f:
            artifacts.append(json.load(f))
    golden = load_golden(args.golden)

    if args.update_golden:
        golden = update_golden(artifacts, golden)
        with open(args.golden, "w") as f:
            json.dump(golden, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"bench-gate: baseline updated for "
              f"{', '.join(a['scenario'] for a in artifacts)} "
              f"-> {args.golden}")
        return 0

    failed = False
    for artifact in artifacts:
        ok, line = gate_artifact(artifact, golden)
        print(f"bench-gate: {line}")
        if not ok:
            failed = True
    if failed:
        cmd = ("python -m copycat_tpu.testing.bench_gate "
               + " ".join(args.artifacts) + " --update-golden")
        print(f"bench-gate: FAILED — if the change is intentional and "
              f"reviewed, refresh the window with:\n  {cmd}")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
