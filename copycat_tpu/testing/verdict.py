"""Linearizability verdict at bench scale (BASELINE.md's "Jepsen pass").

The reference's claim to fame is external Jepsen verification
(``/root/reference/README.md:8``); the in-tree Wing & Gong checker
(:mod:`linearize`) covers it on small histories in tests. This runner
produces the VERDICT ARTIFACT at bench scale: a ``RaftGroups`` batch of
≥10k groups runs under a randomized nemesis (partitions, isolation,
message loss) with client load, histories are recorded on a sample of
groups across three resource models (register/counter, map, try-lock),
and every sampled history is checked. Output: one JSON line on stdout +
``LINEARIZABILITY.md`` rewritten with the verdict.

Run: ``python -m copycat_tpu.testing.verdict`` (env overrides:
``COPYCAT_VERDICT_GROUPS/SAMPLE/ROUNDS/SEED``, plus
``COPYCAT_VERDICT_CHURN=0`` to disable the default membership churn —
with churn on, groups run 5 peer lanes with 3 initial voters and server
join/leave cycles through the voter sets mid-faults).
"""

from __future__ import annotations

import json
import os
import sys
import time

from ..utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()

import numpy as np

from ..models.raft_groups import RaftGroups
from ..ops import apply as ap
from .history import HistoryRecorder
from .linearize import (
    LockModel,
    RegisterModel,
    check_linearizable_windowed,
    check_map_linearizable,
)
from .nemesis import Nemesis

GROUPS = int(os.environ.get("COPYCAT_VERDICT_GROUPS", "10000"))
SAMPLE = int(os.environ.get("COPYCAT_VERDICT_SAMPLE", "99"))
ROUNDS = int(os.environ.get("COPYCAT_VERDICT_ROUNDS", "1000"))
SEED = int(os.environ.get("COPYCAT_VERDICT_SEED", "42"))
# ops per sampled group per round (round-3 depth was one op every 4
# rounds ≈ 100 ops/group; VERDICT r3 #7 wants ≥1k — the windowed checker
# keeps the deeper histories tractable)
OP_EVERY_ROUNDS = max(1, int(os.environ.get("COPYCAT_VERDICT_OP_EVERY", "1")))
# Bounded client concurrency per group (a real client's pipelining
# window): without it a long fault piles up in-flight recorded ops
# (observed: 2,105 pending at round 300), leaving incomplete ops that
# both distort the workload and make the checker's incomplete-op subsets
# explode.
MAX_INFLIGHT = max(1, int(os.environ.get("COPYCAT_VERDICT_INFLIGHT", "4")))
BACKGROUND_PER_ROUND = 500  # untracked load spread over the other groups
# Membership churn (default ON): groups run 5 peer lanes with 3 initial
# voters and the nemesis is joined by server join/leave — every sampled
# group cycles lanes 3/4 in and out of its voter set while its history
# is recorded. Jepsen's hardest configuration for the reference is
# exactly faults + membership changes together; linearizability of
# client ops must hold across config changes.
CHURN = os.environ.get("COPYCAT_VERDICT_CHURN", "1") == "1"
CHURN_PERIOD = 20
CHURN_CYCLE = (("add", 3), ("add", 4), ("remove", 3), ("remove", 4))


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _invoke_register(rec: HistoryRecorder, g: int, rng) -> None:
    kind = int(rng.integers(4))
    if kind == 0:
        v = int(rng.integers(1, 50))
        rec.invoke(g, ap.OP_VALUE_SET, ("set", v), a=v)
    elif kind == 1:
        # half the reads ride the lease-gated ATOMIC query lane (no log
        # append) — the checker validates them against real time, which
        # is exactly the leader-lease soundness claim under test
        query = "atomic" if rng.random() < 0.5 else None
        rec.invoke(g, ap.OP_VALUE_GET, ("get",), query=query)
    elif kind == 2:
        e, u = int(rng.integers(0, 50)), int(rng.integers(1, 50))
        rec.invoke(g, ap.OP_VALUE_CAS, ("cas", e, u), a=e, b=u)
    else:
        d = int(rng.integers(1, 5))
        rec.invoke(g, ap.OP_LONG_ADD, ("add", d), a=d)


def _invoke_map(rec: HistoryRecorder, g: int, rng) -> None:
    kind = int(rng.integers(4))
    k = int(rng.integers(0, 8))
    if kind == 0:
        v = int(rng.integers(1, 99))
        rec.invoke(g, ap.OP_MAP_PUT, ("put", k, v), a=k, b=v)
    elif kind == 1:
        # half the map reads ride the lease-gated ATOMIC query lane too
        # (VERDICT r3 #6: lease reads checked under churn at scale in
        # every model that reads)
        query = "atomic" if rng.random() < 0.5 else None
        rec.invoke(g, ap.OP_MAP_GET, ("get", k), a=k, query=query)
    elif kind == 2:
        rec.invoke(g, ap.OP_MAP_REMOVE, ("remove", k), a=k)
    else:
        rec.invoke(g, ap.OP_MAP_CONTAINS_KEY, ("contains", k), a=k)


def _invoke_lock(rec: HistoryRecorder, g: int, rng) -> None:
    who = int(rng.integers(1, 4))
    if rng.random() < 0.5:
        rec.invoke(g, ap.OP_LOCK_ACQUIRE, ("acquire", who), a=who, b=0)
    else:
        rec.invoke(g, ap.OP_LOCK_RELEASE, ("release", who), a=who)


def run_verdict() -> dict:
    t0 = time.time()
    if CHURN:
        from ..ops.consensus import Config
        rg = RaftGroups(GROUPS, 5, log_slots=64, submit_slots=4, seed=SEED,
                        config=Config(dynamic_membership=True), voters=3)
    else:
        rg = RaftGroups(GROUPS, 3, log_slots=64, submit_slots=4, seed=SEED)
    rg.wait_for_leaders()
    rec = HistoryRecorder(rg)
    nemesis = Nemesis(rg, seed=SEED + 1, period=12)
    rng = np.random.default_rng(SEED + 2)

    # sample split across the three checked models
    sampled = rng.choice(GROUPS, size=SAMPLE, replace=False)
    third = SAMPLE // 3
    reg_groups = [int(g) for g in sampled[:third]]
    map_groups = [int(g) for g in sampled[third:2 * third]]
    lock_groups = [int(g) for g in sampled[2 * third:]]
    others = np.setdiff1d(np.arange(GROUPS), sampled)

    _log(f"verdict: G={GROUPS} sample={SAMPLE} rounds={ROUNDS} "
         f"nemesis period=12 device load={BACKGROUND_PER_ROUND}/round")
    bg_tags: set[int] = set()
    cfg_tags: set[int] = set()
    cfg_submitted = cfg_applied = 0
    churn_step = 0
    for round_no in range(ROUNDS):
        nemesis.tick()
        if CHURN and round_no % CHURN_PERIOD == CHURN_PERIOD // 2:
            # server join/leave on every sampled group (and a slice of
            # the background) while their histories are recorded; the
            # kernel serializes per group, the host requeues early ones
            kind, lane = CHURN_CYCLE[churn_step % len(CHURN_CYCLE)]
            churn_step += 1
            targets = [int(g) for g in sampled]
            targets += [int(g) for g in
                        rng.choice(others, size=min(200, len(others)),
                                   replace=False)]
            for g in targets:
                cfg_tags.add(rg.add_peer(g, lane) if kind == "add"
                             else rg.remove_peer(g, lane))
                cfg_submitted += 1
        # recorded client ops: one per sampled group per OP_EVERY_ROUNDS,
        # gated by the client concurrency window
        if round_no % OP_EVERY_ROUNDS == 0:
            for g in reg_groups:
                if rec.pending_count(g) < MAX_INFLIGHT:
                    _invoke_register(rec, g, rng)
            for g in map_groups:
                if rec.pending_count(g) < MAX_INFLIGHT:
                    _invoke_map(rec, g, rng)
            for g in lock_groups:
                if rec.pending_count(g) < MAX_INFLIGHT:
                    _invoke_lock(rec, g, rng)
        # background load on the rest of the batch (untracked counters —
        # their resolved results are reaped so rg.results stays bounded)
        n_bg = min(BACKGROUND_PER_ROUND, len(others))
        for g in rng.choice(others, size=n_bg, replace=False):
            bg_tags.add(rg.submit(int(g), ap.OP_LONG_ADD, 1))
        rec.tick()
        bg_tags = {t for t in bg_tags if rg.results.pop(t, None) is None}
        done_cfg = {t for t in cfg_tags if t in rg.results}
        cfg_applied += len(done_cfg)
        for t in done_cfg:
            rg.results.pop(t)
        cfg_tags -= done_cfg
        if round_no % 50 == 49:
            _log(f"verdict: round {round_no + 1}/{ROUNDS} "
                 f"fault={nemesis.current} pending={len(rec._pending)}")
    nemesis.heal()
    for _ in range(300):
        if not rec._pending:
            break
        rec.tick()

    checked = failures = undecided = total_ops = total_nodes = 0
    for groups, checker, name in (
            (reg_groups,
             lambda h: check_linearizable_windowed(h, RegisterModel),
             "RegisterModel"),
            (map_groups, check_map_linearizable, "MapModel(per-key)"),
            (lock_groups,
             lambda h: check_linearizable_windowed(h, LockModel),
             "LockModel")):
        for g in groups:
            hist = rec.history(g)
            total_ops += len(hist)
            checked += 1
            try:
                res = checker(hist)
            except RuntimeError as e:
                # search budget exceeded (too-concurrent history): record
                # the group as undecided rather than aborting the run —
                # NEVER counted as a pass (undecided>0 fails the gate)
                undecided += 1
                _log(f"verdict: UNDECIDED group {g} ({name}): {e}")
                continue
            total_nodes += res.nodes
            if not res.ok:
                failures += 1
                _log(f"verdict: VIOLATION group {g} ({name}): {hist}")

    result = {
        "linearizable": failures == 0 and undecided == 0,
        "groups": GROUPS,
        "undecided_groups": undecided,
        "sampled_groups": checked,
        "checked_ops": total_ops,
        "rounds": ROUNDS,
        "nemesis": "partition/isolate/loss, period 12"
                   + (", membership churn" if CHURN else ""),
        "violations": failures,
        "search_nodes": total_nodes,
        "incomplete_ops": len(rec._pending),
        "wall_s": round(time.time() - t0, 1),
        "seed": SEED,
    }
    if CHURN:
        result["membership_changes_applied"] = cfg_applied
        result["membership_changes_submitted"] = cfg_submitted
    return result


def _write_artifact(result: dict) -> None:
    churn_clause = ""
    if "membership_changes_applied" in result:
        churn_clause = (
            " WITH live membership churn (server join/leave cycling"
            " lanes 3/4 of every sampled group's voter set — Jepsen's"
            " hardest configuration:"
            f" {result['membership_changes_applied']:,} config changes"
            " applied mid-faults)")
    lines = [
        "# LINEARIZABILITY — verdict artifact at bench scale",
        "",
        "BASELINE.md's metric line ends \"Jepsen pass\" (the reference's"
        " claim rests on",
        "external Jepsen runs, `README.md:8`). This artifact is the"
        " in-tree equivalent,",
        "produced by `python -m copycat_tpu.testing.verdict`: a"
        f" {result['groups']:,}-group device",
        "batch ran under a randomized nemesis (partitions, single-peer"
        " isolation,",
        "30% message loss; period 12 rounds)" + churn_clause
        + " with client load;"
        f" {result['sampled_groups']}",
        "sampled groups recorded real-time histories across three"
        " resource models",
        "(linearizable register/counter, map, try-lock), each checked"
        " with the",
        "Wing & Gong checker (`copycat_tpu/testing/linearize.py`).",
        "",
        "```json",
        json.dumps(result, indent=2),
        "```",
        "",
        "Semantics of the verdict: every completed operation's result is",
        "explainable by a total order consistent with real-time"
        " (invoke/complete",
        "windows in driver rounds); operations that never completed"
        " (e.g. submitted",
        "into a partitioned leader) may linearize at any point or"
        " never — exactly a",
        "Jepsen client's crashed-request semantics.",
        "",
    ]
    with open("LINEARIZABILITY.md", "w") as f:
        f.write("\n".join(lines))


def main() -> None:
    from ..utils.platform import enable_compilation_cache, require_devices
    require_devices(env="COPYCAT_VERDICT_DEVICE_TIMEOUT")
    enable_compilation_cache()
    result = run_verdict()
    # COPYCAT_VERDICT_ARTIFACT=0 skips rewriting LINEARIZABILITY.md — the
    # committed artifact records the BENCH-scale verdict; smoke runs (CI,
    # local debugging at small GROUPS) must not clobber it.
    if os.environ.get("COPYCAT_VERDICT_ARTIFACT", "1") == "1":
        _write_artifact(result)
    print(json.dumps(result))
    if not result["linearizable"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
