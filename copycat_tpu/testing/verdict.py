"""Linearizability verdict at bench scale (BASELINE.md's "Jepsen pass").

The reference's claim to fame is external Jepsen verification
(``/root/reference/README.md:8``); the in-tree Wing & Gong checker
(:mod:`linearize`) covers it on small histories in tests. This runner
produces the VERDICT ARTIFACT at bench scale: a ``RaftGroups`` batch of
≥10k groups runs under a randomized nemesis (partitions, isolation,
message loss) with client load, histories are recorded on a sample of
groups across three resource models (register/counter, map, try-lock),
and every sampled history is checked. Output: one JSON line on stdout +
``LINEARIZABILITY.md`` rewritten with the verdict.

Run: ``python -m copycat_tpu.testing.verdict`` (env overrides:
``COPYCAT_VERDICT_GROUPS/SAMPLE/ROUNDS/SEED``, plus
``COPYCAT_VERDICT_CHURN=0`` to disable the default membership churn —
with churn on, groups run 5 peer lanes with 3 initial voters and server
join/leave cycles through the voter sets mid-faults).
"""

from __future__ import annotations

import json
import math
import sys
import time

from ..utils import knobs
from ..utils.platform import honor_jax_platforms_env

honor_jax_platforms_env()

import numpy as np

from ..models.raft_groups import RaftGroups
from ..ops import apply as ap
from .history import HistoryRecorder
from .linearize import (
    HOp,
    LockModel,
    RegisterModel,
    check_linearizable_windowed,
    check_map_linearizable,
)
from .nemesis import Nemesis

GROUPS = knobs.get_int("COPYCAT_VERDICT_GROUPS")
SAMPLE = knobs.get_int("COPYCAT_VERDICT_SAMPLE")
ROUNDS = knobs.get_int("COPYCAT_VERDICT_ROUNDS")
SEED = knobs.get_int("COPYCAT_VERDICT_SEED")
# ops per sampled group per round (round-3 depth was one op every 4
# rounds ≈ 100 ops/group; VERDICT r3 #7 wants ≥1k — the windowed checker
# keeps the deeper histories tractable)
OP_EVERY_ROUNDS = max(1, knobs.get_int("COPYCAT_VERDICT_OP_EVERY"))
# Bounded client concurrency per group (a real client's pipelining
# window): without it a long fault piles up in-flight recorded ops
# (observed: 2,105 pending at round 300), leaving incomplete ops that
# both distort the workload and make the checker's incomplete-op subsets
# explode.
MAX_INFLIGHT = max(1, knobs.get_int("COPYCAT_VERDICT_INFLIGHT"))
BACKGROUND_PER_ROUND = 500  # untracked load spread over the other groups
# Membership churn (default ON): groups run 5 peer lanes with 3 initial
# voters and the nemesis is joined by server join/leave — every sampled
# group cycles lanes 3/4 in and out of its voter set while its history
# is recorded. Jepsen's hardest configuration for the reference is
# exactly faults + membership changes together; linearizability of
# client ops must hold across config changes.
CHURN = knobs.get_bool("COPYCAT_VERDICT_CHURN")
CHURN_PERIOD = 20
CHURN_CYCLE = (("add", 3), ("add", 4), ("remove", 3), ("remove", 4))
# Deep-plane block (VERDICT r4 #4): drive the monotone-tag pipelined
# plane — the path the north-star number rides — under per-epoch static
# faults, and Wing-&-Gong-check the recorded histories. Off with
# COPYCAT_VERDICT_DEEP=0.
DEEP = knobs.get_bool("COPYCAT_VERDICT_DEEP")
DEEP_GROUPS = knobs.get_int("COPYCAT_VERDICT_DEEP_GROUPS")
DEEP_SAMPLE = knobs.get_int("COPYCAT_VERDICT_DEEP_SAMPLE")
DEEP_EPOCHS = knobs.get_int("COPYCAT_VERDICT_DEEP_EPOCHS")
DEEP_OPS_PER_EPOCH = 4          # recorded ops / sampled group / epoch


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _invoke_register(rec: HistoryRecorder, g: int, rng) -> None:
    kind = int(rng.integers(4))
    if kind == 0:
        v = int(rng.integers(1, 50))
        rec.invoke(g, ap.OP_VALUE_SET, ("set", v), a=v)
    elif kind == 1:
        # half the reads ride the lease-gated ATOMIC query lane (no log
        # append) — the checker validates them against real time, which
        # is exactly the leader-lease soundness claim under test
        query = "atomic" if rng.random() < 0.5 else None
        rec.invoke(g, ap.OP_VALUE_GET, ("get",), query=query)
    elif kind == 2:
        e, u = int(rng.integers(0, 50)), int(rng.integers(1, 50))
        rec.invoke(g, ap.OP_VALUE_CAS, ("cas", e, u), a=e, b=u)
    else:
        d = int(rng.integers(1, 5))
        rec.invoke(g, ap.OP_LONG_ADD, ("add", d), a=d)


def _invoke_map(rec: HistoryRecorder, g: int, rng) -> None:
    kind = int(rng.integers(4))
    k = int(rng.integers(0, 8))
    if kind == 0:
        v = int(rng.integers(1, 99))
        rec.invoke(g, ap.OP_MAP_PUT, ("put", k, v), a=k, b=v)
    elif kind == 1:
        # half the map reads ride the lease-gated ATOMIC query lane too
        # (VERDICT r3 #6: lease reads checked under churn at scale in
        # every model that reads)
        query = "atomic" if rng.random() < 0.5 else None
        rec.invoke(g, ap.OP_MAP_GET, ("get", k), a=k, query=query)
    elif kind == 2:
        rec.invoke(g, ap.OP_MAP_REMOVE, ("remove", k), a=k)
    else:
        rec.invoke(g, ap.OP_MAP_CONTAINS_KEY, ("contains", k), a=k)


def _invoke_lock(rec: HistoryRecorder, g: int, rng) -> None:
    who = int(rng.integers(1, 4))
    if rng.random() < 0.5:
        rec.invoke(g, ap.OP_LOCK_ACQUIRE, ("acquire", who), a=who, b=0)
    else:
        rec.invoke(g, ap.OP_LOCK_RELEASE, ("release", who), a=who)


def _telemetry_summary(rg) -> dict:
    """Final device.* telemetry + invariant-monitor verdict for the JSON
    artifact (fields documented in LINEARIZABILITY.md). The monitor ran
    on EVERY fetched round of the run, so violations==0 here is an
    online safety witness alongside the offline Wing & Gong check."""
    hub = getattr(rg, "telemetry", None)
    if hub is None:
        return {}
    out = {k: v for k, v in hub.snapshot().items()
           if k.startswith("device.") and not isinstance(v, dict)}
    out["invariants"] = hub.monitor.summary()
    return out


def run_verdict() -> dict:
    from ..ops.consensus import Config

    t0 = time.time()
    if CHURN:
        rg = RaftGroups(GROUPS, 5, log_slots=64, submit_slots=4, seed=SEED,
                        config=Config(dynamic_membership=True,
                                      telemetry=True), voters=3)
    else:
        rg = RaftGroups(GROUPS, 3, log_slots=64, submit_slots=4, seed=SEED,
                        config=Config(telemetry=True))
    rg.wait_for_leaders()
    rec = HistoryRecorder(rg)
    nemesis = Nemesis(rg, seed=SEED + 1, period=12)
    rng = np.random.default_rng(SEED + 2)

    # sample split across the three checked models
    sampled = rng.choice(GROUPS, size=SAMPLE, replace=False)
    third = SAMPLE // 3
    reg_groups = [int(g) for g in sampled[:third]]
    map_groups = [int(g) for g in sampled[third:2 * third]]
    lock_groups = [int(g) for g in sampled[2 * third:]]
    others = np.setdiff1d(np.arange(GROUPS), sampled)

    _log(f"verdict: G={GROUPS} sample={SAMPLE} rounds={ROUNDS} "
         f"nemesis period=12 device load={BACKGROUND_PER_ROUND}/round")
    bg_tags: set[int] = set()
    cfg_tags: set[int] = set()
    cfg_submitted = cfg_applied = 0
    churn_step = 0
    for round_no in range(ROUNDS):
        nemesis.tick()
        if CHURN and round_no % CHURN_PERIOD == CHURN_PERIOD // 2:
            # server join/leave on every sampled group (and a slice of
            # the background) while their histories are recorded; the
            # kernel serializes per group, the host requeues early ones
            kind, lane = CHURN_CYCLE[churn_step % len(CHURN_CYCLE)]
            churn_step += 1
            targets = [int(g) for g in sampled]
            targets += [int(g) for g in
                        rng.choice(others, size=min(200, len(others)),
                                   replace=False)]
            for g in targets:
                cfg_tags.add(rg.add_peer(g, lane) if kind == "add"
                             else rg.remove_peer(g, lane))
                cfg_submitted += 1
        # recorded client ops: one per sampled group per OP_EVERY_ROUNDS,
        # gated by the client concurrency window
        if round_no % OP_EVERY_ROUNDS == 0:
            for g in reg_groups:
                if rec.pending_count(g) < MAX_INFLIGHT:
                    _invoke_register(rec, g, rng)
            for g in map_groups:
                if rec.pending_count(g) < MAX_INFLIGHT:
                    _invoke_map(rec, g, rng)
            for g in lock_groups:
                if rec.pending_count(g) < MAX_INFLIGHT:
                    _invoke_lock(rec, g, rng)
        # background load on the rest of the batch (untracked counters —
        # their resolved results are reaped so rg.results stays bounded)
        n_bg = min(BACKGROUND_PER_ROUND, len(others))
        for g in rng.choice(others, size=n_bg, replace=False):
            bg_tags.add(rg.submit(int(g), ap.OP_LONG_ADD, 1))
        rec.tick()
        bg_tags = {t for t in bg_tags if rg.results.pop(t, None) is None}
        done_cfg = {t for t in cfg_tags if t in rg.results}
        cfg_applied += len(done_cfg)
        for t in done_cfg:
            rg.results.pop(t)
        cfg_tags -= done_cfg
        if round_no % 50 == 49:
            _log(f"verdict: round {round_no + 1}/{ROUNDS} "
                 f"fault={nemesis.current} pending={len(rec._pending)}")
    nemesis.heal()
    for _ in range(300):
        if not rec._pending:
            break
        rec.tick()

    checked = failures = undecided = total_ops = total_nodes = 0
    for groups, checker, name in (
            (reg_groups,
             lambda h: check_linearizable_windowed(h, RegisterModel),
             "RegisterModel"),
            (map_groups, check_map_linearizable, "MapModel(per-key)"),
            (lock_groups,
             lambda h: check_linearizable_windowed(h, LockModel),
             "LockModel")):
        for g in groups:
            hist = rec.history(g)
            total_ops += len(hist)
            checked += 1
            try:
                res = checker(hist)
            except RuntimeError as e:
                # search budget exceeded (too-concurrent history): record
                # the group as undecided rather than aborting the run —
                # NEVER counted as a pass (undecided>0 fails the gate)
                undecided += 1
                _log(f"verdict: UNDECIDED group {g} ({name}): {e}")
                continue
            total_nodes += res.nodes
            if not res.ok:
                failures += 1
                _log(f"verdict: VIOLATION group {g} ({name}): {hist}")

    result = {
        "linearizable": failures == 0 and undecided == 0,
        "groups": GROUPS,
        "undecided_groups": undecided,
        "sampled_groups": checked,
        "checked_ops": total_ops,
        "rounds": ROUNDS,
        "nemesis": "partition/isolate/loss, period 12"
                   + (", membership churn" if CHURN else ""),
        "violations": failures,
        "search_nodes": total_nodes,
        "incomplete_ops": len(rec._pending),
        "wall_s": round(time.time() - t0, 1),
        "seed": SEED,
        "device_telemetry": _telemetry_summary(rg),
    }
    if CHURN:
        result["membership_changes_applied"] = cfg_applied
        result["membership_changes_submitted"] = cfg_submitted
    return result


def run_deep_verdict() -> dict:
    """Wing & Gong verdict for the DEEP (monotone-tag) client plane.

    The round-4 headline number comes from ``models/bulk.py``'s deep
    pipelined drive, whose exactly-once story was argued in docstrings
    but never driven by this harness (VERDICT r4 weak #5). This block
    drives it: per epoch a static fault mask (heal / 30% loss /
    2-side partition / single-peer isolation — the envelope whose
    liveness the plane supports via its phase-2 suffix retries) is
    installed, every sampled group commits a burst of recorded register
    ops through ``BulkDriver.drive`` (device-gated FIFO + dedup), half
    the epochs also serve lease-gated ATOMIC reads through
    ``drive_queries``, and real-time windows come from the drive's
    per-op dispatch/resolve rounds. A drive that exceeds its round
    budget (liveness lost under a static mask) marks its burst
    maybe-applied — the Jepsen crashed-client treatment — and recovers
    via ``BulkDriver.recover`` (heal → settle → cursor resync), which is
    exactly the protocol a production client must follow.
    """
    from ..models.bulk import BulkDriver
    from ..ops.consensus import Config

    t0 = time.time()
    rg = RaftGroups(DEEP_GROUPS, 3, log_slots=64, submit_slots=4,
                    seed=SEED + 10,
                    config=Config(monotone_tag_accept=True,
                                  telemetry=True))
    rg.wait_for_leaders()
    driver = BulkDriver(rg)
    rng = np.random.default_rng(SEED + 11)
    nemesis = Nemesis(rg, seed=SEED + 12)

    sampled = [int(g) for g in
               rng.choice(DEEP_GROUPS, size=DEEP_SAMPLE, replace=False)]
    others = np.setdiff1d(np.arange(DEEP_GROUPS), sampled)
    # Histories are kept as SEGMENTS of (init_state, ops): an aborted
    # drive leaves maybe-applied (forever-incomplete) ops, and every
    # incomplete op blocks all later quiescent cuts — a few aborts would
    # collapse the rest of the run into one exponential checker segment.
    # recover() is a FENCE (an abandoned op can never apply after it),
    # so after each abort the current segment is closed with an ANCHOR —
    # a lease-gated linearizable read whose value both constrains the
    # closing segment's linearization and seeds the next segment's
    # init_state.
    segments: dict[int, list] = {g: [] for g in sampled}
    cur_ops: dict[int, list] = {g: [] for g in sampled}
    cur_init: dict[int, int] = {g: 0 for g in sampled}
    op_id = [0]
    drive_aborts = anchor_timeouts = 0

    def _epoch_ops():
        """One recorded burst: DEEP_OPS_PER_EPOCH register ops per
        sampled group + untracked background adds on other groups."""
        gs, ops, av, bv, labels = [], [], [], [], []
        for g in sampled:
            for _ in range(DEEP_OPS_PER_EPOCH):
                kind = int(rng.integers(4))
                if kind == 0:
                    v = int(rng.integers(1, 50))
                    gs.append(g); ops.append(ap.OP_VALUE_SET)
                    av.append(v); bv.append(0); labels.append(("set", v))
                elif kind == 1:
                    gs.append(g); ops.append(ap.OP_VALUE_GET)
                    av.append(0); bv.append(0); labels.append(("get",))
                elif kind == 2:
                    e, u = int(rng.integers(0, 50)), int(rng.integers(1, 50))
                    gs.append(g); ops.append(ap.OP_VALUE_CAS)
                    av.append(e); bv.append(u); labels.append(("cas", e, u))
                else:
                    d = int(rng.integers(1, 5))
                    gs.append(g); ops.append(ap.OP_LONG_ADD)
                    av.append(d); bv.append(0); labels.append(("add", d))
        n_rec = len(gs)
        bg = rng.choice(others, size=min(400, len(others)), replace=False)
        gs += [int(g) for g in bg]
        ops += [ap.OP_LONG_ADD] * len(bg)
        av += [1] * len(bg)
        bv += [0] * len(bg)
        return (np.asarray(gs), np.asarray(ops), np.asarray(av),
                np.asarray(bv), labels, n_rec)

    _log(f"deep verdict: G={DEEP_GROUPS} sample={DEEP_SAMPLE} "
         f"epochs={DEEP_EPOCHS} x {DEEP_OPS_PER_EPOCH} ops/group")
    import jax.numpy as jnp
    heal_mask = jnp.asarray(nemesis._mask("heal"))
    for epoch in range(DEEP_EPOCHS):
        fault = ("heal", "loss", "partition", "isolate")[
            int(rng.integers(4))]
        # the fault lasts FAULT_ROUNDS of the drive, then heals — the
        # deep plane's liveness envelope is faults-with-recovery (its
        # phase-2 suffix retries then resolve everything); a fault held
        # static forever is a liveness loss by design, exercised
        # separately by the abort path below
        fault_mask = jnp.asarray(nemesis._mask(fault))
        fault_rounds = int(rng.integers(6, 16))
        schedule = (lambda r, fm=fault_mask, fr=fault_rounds:
                    fm if r % 60 < fr else heal_mask)
        budget = 400
        if epoch % 7 == 6 and fault != "heal":
            # every 7th epoch the fault is held STATIC with a small round
            # budget: the drive must lose liveness (by design), abort,
            # mark its burst maybe-applied, and walk the recover()
            # protocol — the crashed-client path checked at scale
            schedule = lambda r, fm=fault_mask: fm  # noqa: E731
            budget = 120
        gs, ops, av, bv, labels, n_rec = _epoch_ops()
        base_round = rg.rounds
        try:
            res = driver.drive(gs, ops, av, bv, max_rounds=budget,
                               deliver_schedule=schedule)
        except TimeoutError:
            drive_aborts += 1
            for k in range(n_rec):
                op_id[0] += 1
                cur_ops[int(gs[k])].append(HOp(
                    op_id=op_id[0], op=labels[k], result=None,
                    invoke=base_round, complete=math.inf))
            nemesis.heal()
            driver.recover(settle_rounds=30)
            # fence + anchor: close every group's segment on a
            # linearizable read of the post-recovery state
            fence = rg.rounds
            try:
                vals = driver.drive_queries(
                    np.asarray(sampled), ap.OP_VALUE_GET,
                    consistency="atomic", max_rounds=200)
            except TimeoutError:
                anchor_timeouts += 1  # rare: keep segments open
            else:
                for g, v in zip(sampled, vals):
                    op_id[0] += 1
                    cur_ops[g].append(HOp(
                        op_id=op_id[0], op=("get",), result=int(v),
                        invoke=fence, complete=rg.rounds))
                    segments[g].append((cur_init[g], cur_ops[g]))
                    cur_ops[g] = []
                    cur_init[g] = int(v)
            continue
        for k in range(n_rec):
            op_id[0] += 1
            cur_ops[int(gs[k])].append(HOp(
                op_id=op_id[0], op=labels[k],
                result=int(res.results[k]),
                invoke=base_round + int(res.dispatch_round[k]),
                complete=base_round + int(res.resolve_round[k])))
        if epoch % 2 == 1:
            # lease-gated linearizable reads through the query lane
            # (no log append) — windows span the whole call, which is
            # sound (wider window = more permissive)
            nemesis.heal()  # static faults would starve the lease gate
            q0 = rg.rounds
            try:
                vals = driver.drive_queries(
                    np.asarray(sampled), ap.OP_VALUE_GET,
                    consistency="atomic", max_rounds=200)
            except TimeoutError:
                anchor_timeouts += 1
            else:
                for g, v in zip(sampled, vals):
                    op_id[0] += 1
                    cur_ops[g].append(HOp(
                        op_id=op_id[0], op=("get",), result=int(v),
                        invoke=q0, complete=rg.rounds))
        if epoch % 10 == 9:
            _log(f"deep verdict: epoch {epoch + 1}/{DEEP_EPOCHS} "
                 f"rounds={rg.rounds} aborted={drive_aborts}")
    nemesis.heal()
    for g in sampled:
        segments[g].append((cur_init[g], cur_ops[g]))

    checked = failures = undecided = total_ops = nodes = 0
    incomplete = 0
    for g in sampled:
        checked += 1
        bad = und = False
        for init, seg in segments[g]:
            hist = sorted(seg, key=lambda h: (h.invoke, h.op_id))
            total_ops += len(hist)
            incomplete += sum(1 for h in hist if h.result is None)
            try:
                res = check_linearizable_windowed(hist, RegisterModel,
                                                  init_state=init)
            except RuntimeError as e:
                und = True
                _log(f"deep verdict: UNDECIDED group {g}: {e}")
                continue
            nodes += res.nodes
            if not res.ok:
                bad = True
                _log(f"deep verdict: VIOLATION group {g} "
                     f"(segment init={init}): {hist}")
        failures += bad
        undecided += und

    return {
        "linearizable": failures == 0 and undecided == 0,
        "groups": DEEP_GROUPS,
        "sampled_groups": checked,
        "checked_ops": total_ops,
        "incomplete_ops": incomplete,
        "epochs": DEEP_EPOCHS,
        "aborted_drives": drive_aborts,
        "anchor_timeouts": anchor_timeouts,
        "undecided_groups": undecided,
        "violations": failures,
        "search_nodes": nodes,
        "wall_s": round(time.time() - t0, 1),
        "seed": SEED,
        "device_telemetry": _telemetry_summary(rg),
    }


def _write_artifact(result: dict) -> None:
    churn_clause = ""
    if "membership_changes_applied" in result:
        churn_clause = (
            " WITH live membership churn (server join/leave cycling"
            " lanes 3/4 of every sampled group's voter set — Jepsen's"
            " hardest configuration:"
            f" {result['membership_changes_applied']:,} config changes"
            " applied mid-faults)")
    lines = [
        "# LINEARIZABILITY — verdict artifact at bench scale",
        "",
        "BASELINE.md's metric line ends \"Jepsen pass\" (the reference's"
        " claim rests on",
        "external Jepsen runs, `README.md:8`). This artifact is the"
        " in-tree equivalent,",
        "produced by `python -m copycat_tpu.testing.verdict`: a"
        f" {result['groups']:,}-group device",
        "batch ran under a randomized nemesis (partitions, single-peer"
        " isolation,",
        "30% message loss; period 12 rounds)" + churn_clause
        + " with client load;"
        f" {result['sampled_groups']}",
        "sampled groups recorded real-time histories across three"
        " resource models",
        "(linearizable register/counter, map, try-lock), each checked"
        " with the",
        "Wing & Gong checker (`copycat_tpu/testing/linearize.py`).",
        "",
        "```json",
        json.dumps(result, indent=2),
        "```",
        "",
        "Semantics of the verdict: every completed operation's result is",
        "explainable by a total order consistent with real-time"
        " (invoke/complete",
        "windows in driver rounds); operations that never completed"
        " (e.g. submitted",
        "into a partitioned leader) may linearize at any point or"
        " never — exactly a",
        "Jepsen client's crashed-request semantics.",
        "",
        "## Device telemetry fields (round 8)",
        "",
        "`device_telemetry` (and `deep_plane.device_telemetry`) embed the"
        " run's final",
        "device-plane flight-recorder counters"
        " (docs/OBSERVABILITY.md § device plane):",
        "`device.elections_started`, `device.leader_changes`,"
        " `device.term_bumps`,",
        "`device.leaderless_rounds` (group-rounds without a leader),",
        "`device.commit_advance`, `device.submit_rejections`"
        " (backpressure/lease-gate",
        "requeues), `device.vote_splits`, `device.events_drained` /"
        " `_dropped`, and",
        "`device.applies{pool=...}` — all accumulated from the jitted"
        " step's on-device",
        "reductions across every round of the run. `invariants` is the"
        " ONLINE monitor's",
        "verdict: `{mode, violations, watched_groups, leaderless_max}` —"
        " per-fetch checks",
        "of commit-total/per-group commit monotonicity, per-group leader-"
        "term",
        "monotonicity, the leaderless-fraction bound, and a sampled"
        " ≤1-leader-per-term",
        "watch-list. `violations: 0` means no fetched round ever"
        " contradicted Raft's",
        "safety claims while the nemesis ran; under"
        " `COPYCAT_INVARIANTS=strict` the run",
        "would have aborted at the first violation instead.",
        "",
    ]
    if "deep_plane" in result:
        d = result["deep_plane"]
        lines += [
            "## Deep (monotone-tag) client plane",
            "",
            "The flagship throughput number rides `models/bulk.py`'s deep"
            " pipelined",
            "drive (device-enforced FIFO + dedup, zero blocking fetches)."
            " This block is",
            "the same Wing & Gong harness pointed at THAT plane"
            f" (round-5, VERDICT r4 #4): {d['groups']:,}",
            f"groups, {d['sampled_groups']} sampled, {d['epochs']} epochs"
            " of per-epoch static faults (heal/30% loss/",
            "2-side partition/peer isolation) with recorded register"
            " bursts through",
            "`BulkDriver.drive` and lease-gated ATOMIC reads through the"
            " query lane.",
            f"Command drives that lost liveness under a static mask"
            f" ({d['aborted_drives']} of {d['epochs']}) marked their"
            " bursts",
            "maybe-applied, recovered via `BulkDriver.recover`"
            " (heal → settle → cursor",
            "resync — the fence that makes post-abandon tag reuse"
            " impossible), and the",
            "history was re-anchored on a lease-gated linearizable read"
            " that both",
            "constrains the closing segment and seeds the next one.",
            "",
        ]
    with open("LINEARIZABILITY.md", "w") as f:
        f.write("\n".join(lines))


def main() -> None:
    from ..utils.platform import enable_compilation_cache, require_devices
    require_devices(env="COPYCAT_VERDICT_DEVICE_TIMEOUT")
    enable_compilation_cache()
    result = run_verdict()
    if DEEP:
        deep = run_deep_verdict()
        result["deep_plane"] = deep
        result["linearizable"] = result["linearizable"] and \
            deep["linearizable"]
    # COPYCAT_VERDICT_ARTIFACT=0 skips rewriting LINEARIZABILITY.md — the
    # committed artifact records the BENCH-scale verdict; smoke runs (CI,
    # local debugging at small GROUPS) must not clobber it.
    if knobs.get_bool("COPYCAT_VERDICT_ARTIFACT"):
        _write_artifact(result)
    print(json.dumps(result))
    if not result["linearizable"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
