"""Edge read tier: client-local CRDT replicas serving CAUSAL/SEQUENTIAL
reads without a server round-trip (docs/EDGE_READS.md).

The client subscribes to per-resource state deltas over the existing
session event channels (``PublishRequest.deltas``, an optional trailing
wire field) and keeps a replica per queried instance: ``(version,
tagged state)`` where ``version`` is the owning group's applied log
index at publication time. Because the log totally orders versions,
``merge(local, delta) = max-version-wins`` is a join-semilattice merge —
idempotent, commutative, associative — so duplicated, reordered, or
re-delivered-after-failover deltas converge instead of corrupting
(PAPERS.md: "Linearizable State Machine Replication of State-Based
CRDTs without Logs").

Serving is gated twice:

- **monotone/read-your-writes gate**: a replica entry serves only while
  its version is at or past the client's per-group read index — the
  SAME index space server-side sequential reads wait on, so a local
  serve is indistinguishable from a server read at that index (and
  advances the index like one);
- **staleness gate**: an entry that saw no delta or re-seed for
  ``COPYCAT_EDGE_TTL_S`` stops serving — the next read re-seeds from
  the server (which also heals a subscription lost to failover or
  re-route, since the registry is member-local).

Memory is bounded: ``COPYCAT_EDGE_MAX_RESOURCES`` entries, LRU-evicted
back to server reads; evictions unsubscribe via the next keep-alive's
``unsubscribe`` field.

Evaluation is by (state tag, query op type) — machine-class agnostic,
so the CPU and device-backed machines of one resource type share one
evaluator. Ops without an evaluator (or resources the server never
seeds) simply keep the server path.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Any

from ..atomic import commands as vc
from ..collections import commands as cc
from ..manager.operations import InstanceQuery
from ..resource.operations import ResourceQuery
from ..utils import knobs
from ..utils.tracing import TRACER

#: sentinel distinguishing "cannot serve" from a served None result
MISS = object()

#: (state tag, inner query op type) -> evaluator over the tagged
#: payload. Each evaluator must return exactly what the server-side
#: handler returns for the same state (the knob-off differential in
#: tests/test_edge_reads.py pins that).
_EVAL = {
    ("val", vc.Get): lambda s, op: s,
    ("map", cc.MapGet): lambda s, op: s.get(op.key),
    ("map", cc.MapGetOrDefault):
        lambda s, op: s[op.key] if op.key in s else op.default,
    ("map", cc.MapContainsKey): lambda s, op: op.key in s,
    ("map", cc.MapContainsValue):
        lambda s, op: any(v == op.value for v in s.values()),
    ("map", cc.MapSize): lambda s, op: len(s),
    ("map", cc.MapIsEmpty): lambda s, op: not s,
    ("set", cc.SetContains): lambda s, op: op.value in s,
    ("set", cc.SetSize): lambda s, op: len(s),
    ("set", cc.SetIsEmpty): lambda s, op: not s,
}


class _Entry:
    """One replica entry. ``version`` is the CERTIFIED version (the
    largest log index the server asserted this entry's state current
    at — the monotone gate's input); ``state_version`` is the version
    of the last STATE record merged. Keeping them separate makes the
    merge a true join in both components: states join by max
    ``state_version``, certification joins by max ``version``, so any
    arrival permutation of the same record set converges identically
    (a refresh arriving before the state deltas it post-dates no
    longer drops them)."""

    __slots__ = ("version", "state_version", "tag", "state", "expires")

    def __init__(self, version: int, tag: str, state: Any,
                 expires: float) -> None:
        self.version = version
        self.state_version = version
        self.tag = tag
        self.state = state
        self.expires = expires


def _split(record: Any) -> tuple[str, Any] | None:
    """Unpack one tagged state payload; ``None`` for the retire form."""
    if record is None:
        return None
    tag, payload = record
    if tag == "set":
        payload = set(payload)
    return tag, payload


class EdgeReadTier:
    """One client's replica store + subscription manager."""

    #: re-exported so the client's fast path never imports this module
    #: at module scope (the manager package import chain would cycle)
    MISS = MISS

    def __init__(self, client: Any) -> None:
        self._client = client
        self._replica: OrderedDict[int, _Entry] = OrderedDict()
        self._pending_unsub: list[int] = []
        # instances whose subscribing reads came back WITHOUT a seed
        # (server declined: not edge-servable, or a seedless ingress
        # tier in front) -> don't re-ask until the deadline, so the
        # follower round-robin isn't permanently pinned to the session
        # connection by hopeless subscribe attempts
        self._no_seed: dict[int, float] = {}
        self._cap = max(1, knobs.get_int("COPYCAT_EDGE_MAX_RESOURCES"))
        self._ttl = knobs.get_float("COPYCAT_EDGE_TTL_S")
        m = client.metrics
        self._m_serves = m.counter("edge.local_serves")
        self._m_fallbacks = m.counter("edge.server_fallbacks")
        self._m_deltas = m.counter("edge.deltas_in")
        self._m_merges = m.counter("edge.merges")
        self._m_seeds = m.counter("edge.seeds")
        self._m_evictions = m.counter("edge.evictions")
        self._m_stale = m.counter("edge.stale_rejections")
        self._m_entries = m.gauge("edge.replica_entries")

    # -- serving -----------------------------------------------------------

    @staticmethod
    def _eligible(operation: Any) -> Any | None:
        """The inner query op when ``operation`` is an edge-shaped read
        (InstanceQuery over ResourceQuery with a known evaluator op
        type), else ``None``."""
        if type(operation) is not InstanceQuery:
            return None
        envelope = operation.operation
        if type(envelope) is not ResourceQuery:
            return None
        return envelope.operation

    def try_serve(self, operation: Any) -> Any:
        """Serve one CAUSAL/SEQUENTIAL read from the replica, or
        :data:`MISS`. A hit records a ``client.edge_serve`` span (its
        assembled trace consists solely of client-side spans — the
        cache-served proof the fanout CI asserts) and advances the
        client's per-group read index to the served version, exactly as
        a server read's response index would."""
        inner = self._eligible(operation)
        if inner is None:
            return MISS
        iid = operation.resource
        entry = self._replica.get(iid)
        if entry is None:
            self._m_fallbacks.inc()
            return MISS
        fn = _EVAL.get((entry.tag, type(inner)))
        if fn is None:
            self._m_fallbacks.inc()
            return MISS
        client = self._client
        groups = client._num_groups
        g = iid % groups
        t0 = time.perf_counter() if TRACER.enabled else 0.0
        if time.monotonic() >= entry.expires \
                or entry.version < client._indices.get(g, 0):
            # staleness gate (no delta/seed for TTL) or monotone/RYW
            # gate (the session observed a newer group index than the
            # replica): fall back, re-seed via the subscribing read
            self._m_stale.inc()
            self._m_fallbacks.inc()
            return MISS
        try:
            result = fn(entry.state, inner)
        except Exception:  # noqa: BLE001 — let the server produce the error
            self._m_fallbacks.inc()
            return MISS
        self._replica.move_to_end(iid)
        self._m_serves.inc()
        # a local serve IS a sequential read at `version`: advance the
        # same per-group high-water a server response index would
        client._note_index(entry.version * groups + g if groups > 1
                           else entry.version)
        if TRACER.enabled:
            TRACER.span(TRACER.new_trace(), "client.edge_serve", t0,
                        time.perf_counter(), member="client", iid=iid)
        return result

    def wants_subscribe(self, items: list) -> bool:
        """True when any remaining read is edge-shaped and not
        negative-cached — the outgoing request then carries
        ``subscribe`` and routes over the session connection (the
        member that can push deltas)."""
        now = time.monotonic()
        return any(
            self._eligible(op) is not None
            and self._no_seed.get(op.resource, 0.0) <= now
            for op, _ in items)

    def seed_response(self, items: list, records: Any) -> None:
        """Install a subscribing read's seeds, and negative-cache the
        edge-shaped instances the server declined to seed (retried
        after one staleness-TTL interval)."""
        seeded = set()
        if records:
            self.seed(records)
            seeded = {iid for iid, _, _ in records}
        retry_at = time.monotonic() + self._ttl
        for op, _ in items:
            if self._eligible(op) is None:
                continue
            if op.resource in seeded:
                self._no_seed.pop(op.resource, None)
            else:
                self._no_seed[op.resource] = retry_at
        if len(self._no_seed) > 4 * self._cap:
            now = time.monotonic()
            self._no_seed = {i: t for i, t in self._no_seed.items()
                             if t > now}

    # -- replica maintenance ----------------------------------------------

    def _adopt(self, iid: int, version: int, tag: str, state: Any) -> None:
        while len(self._replica) >= self._cap:
            evicted, _ = self._replica.popitem(last=False)
            self._pending_unsub.append(evicted)
            self._m_evictions.inc()
        if self._pending_unsub:
            # re-seeded before the eviction's keep-alive went out: the
            # server just re-registered this subscription — retiring it
            # now would starve a LIVE entry of deltas until the TTL
            self._pending_unsub = [x for x in self._pending_unsub
                                   if x != iid]
        self._replica[iid] = _Entry(version, tag, state,
                                    time.monotonic() + self._ttl)
        self._m_entries.set(len(self._replica))

    def _merge(self, iid: int, version: int, record: Any,
               adopt: bool) -> None:
        """join-semilattice merge: max version wins; equal versions are
        idempotent re-applies; ``record=None`` retires the entry; the
        ``("r", None)`` refresh form certifies the entry's existing
        state current at ``version`` (bump version + TTL, keep state)."""
        entry = self._replica.get(iid)
        split = _split(record)
        if split is None:
            if entry is not None:
                del self._replica[iid]
                self._m_entries.set(len(self._replica))
            return
        tag, state = split
        if tag == "r":
            if entry is not None:
                if version > entry.version:
                    entry.version = version
                entry.expires = time.monotonic() + self._ttl
            return
        if entry is None:
            if adopt:
                self._adopt(iid, version, tag, state)
            return  # unadopted delta (evicted/unknown instance): drop
        if version >= entry.state_version:
            entry.state_version = version
            entry.tag = tag
            entry.state = state
            self._m_merges.inc()
        if version > entry.version:
            entry.version = version
        entry.expires = time.monotonic() + self._ttl

    def seed(self, records: Any) -> None:
        """Install the seeds of a subscribing read's response."""
        if not records:
            return
        for iid, version, record in records:
            self._m_seeds.inc()
            self._merge(iid, version or 0, record, adopt=True)

    def ingest(self, deltas: list, trace: int | None = None) -> None:
        """Merge one push's deltas; never adopts (deltas for instances
        the LRU evicted stay dropped until a read re-seeds them)."""
        t0 = time.perf_counter() if trace is not None else 0.0
        self._m_deltas.inc(len(deltas))
        for iid, version, record in deltas:
            self._merge(iid, version or 0, record, adopt=False)
        if trace is not None:
            # delta delivery on the originating write's causal timeline,
            # like `client.event` for session events
            TRACER.span(trace, "client.delta", t0, time.perf_counter(),
                        member="client", n=len(deltas))

    def take_unsubscribes(self) -> list[int] | None:
        """Evicted instance ids staged for the next keep-alive."""
        if not self._pending_unsub:
            return None
        out, self._pending_unsub = self._pending_unsub, []
        return out

    def restage_unsubscribes(self, ids: list[int] | None) -> None:
        """A failed keep-alive re-stages its unsubscribes (retiring a
        subscription is idempotent server-side)."""
        if ids:
            self._pending_unsub.extend(ids)
