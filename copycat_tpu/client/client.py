"""The client runtime: sessions, exactly-once submission, consistency routing.

Mirrors the consumed Copycat client surface (SURVEY.md §2.3 "Client runtime"):
``submit(Command/Query)`` with consistency-dependent routing (commands and
LINEARIZABLE/BOUNDED queries to the leader; SEQUENTIAL/CAUSAL queries to any
server), ``ConnectionStrategy`` (the reference's AtomixReplica pins its client
to the colocated server — ``CombinedConnectionStrategy``), client-assigned
command sequence numbers for exactly-once application, keep-alives, and the
session event channel (``Session.publish/onEvent`` by event name).
"""

from __future__ import annotations

import asyncio
import itertools
import random
import time
import uuid
from typing import Any, Callable

from ..io.transport import Address, Connection, Transport, TransportError
from ..protocol import messages as msg
from ..protocol.operations import Command, Operation, Query
from ..utils import knobs
from ..utils.listeners import Listener, Listeners
from ..utils.managed import Managed
from ..utils.metrics import MetricsRegistry
from ..utils.scheduled import Scheduled
from ..utils.tasks import spawn
from ..utils.tracing import TRACER

_client_counter = itertools.count()


class ApplicationError(Exception):
    """A state machine raised while applying the operation."""


class SessionExpiredError(Exception):
    """The server expired this client's session (missed keep-alives)."""


class ConnectionStrategy:
    """Orders servers for connection attempts."""

    def order(self, members: list[Address]) -> list[Address]:  # pragma: no cover
        raise NotImplementedError


class AnyConnectionStrategy(ConnectionStrategy):
    def order(self, members: list[Address]) -> list[Address]:
        shuffled = list(members)
        random.shuffle(shuffled)
        return shuffled


class PinnedConnectionStrategy(ConnectionStrategy):
    """Always try a specific server first (the reference replica's
    ``CombinedConnectionStrategy`` — client pinned to the in-process server)."""

    def __init__(self, address: Address) -> None:
        self.address = address

    def order(self, members: list[Address]) -> list[Address]:
        rest = [m for m in members if m != self.address]
        random.shuffle(rest)
        return [self.address] + rest


class ClientSession:
    """Client-side session state + event dispatch (Copycat ``Session``)."""

    def __init__(self, client: "RaftClient") -> None:
        self._client = client
        self.id: int | None = None
        self.timeout = 0.0
        self.state = "closed"  # closed -> open -> expired/closed
        # Per-group event channels (docs/SHARDING.md): a multi-group
        # server numbers each group's event stream independently; the
        # single-group plane lives entirely in key 0 (the legacy scalar,
        # via the ``event_index`` property).
        self._event_indices: dict[int, int] = {}
        self._event_listeners: dict[str, Listeners] = {}
        self._open_listeners = Listeners()
        self._close_listeners = Listeners()

    def on_event(self, event: str, callback: Callable[[Any], Any]) -> Listener:
        return self._event_listeners.setdefault(event, Listeners()).add(callback)

    def on_open(self, callback: Callable[[Any], Any]) -> Listener:
        return self._open_listeners.add(callback)

    def on_close(self, callback: Callable[[Any], Any]) -> Listener:
        return self._close_listeners.add(callback)

    @property
    def event_index(self) -> int:
        return self._event_indices.get(0, 0)

    @event_index.setter
    def event_index(self, value: int) -> None:
        self._event_indices[0] = value

    @property
    def is_open(self) -> bool:
        return self.state == "open"

    @property
    def is_expired(self) -> bool:
        return self.state == "expired"

    def publish(self, event: str, message: Any = None) -> None:
        """Local loopback publish (client-side listeners only)."""
        self._dispatch(event, message)

    def _dispatch(self, event: str, message: Any) -> None:
        listeners = self._event_listeners.get(event)
        if listeners is not None:
            listeners.accept(message)

    def _opened(self) -> None:
        self.state = "open"
        self._open_listeners.accept(self)

    def _expired(self) -> None:
        if self.state != "expired":
            self.state = "expired"
            self._close_listeners.accept(self)

    def _closed(self) -> None:
        if self.state == "open":
            self.state = "closed"
            self._close_listeners.accept(self)


class RaftClient(Managed):
    """Submits commands/queries to a Raft cluster over one live connection."""

    def __init__(
        self,
        members: list[Address],
        transport: Transport,
        session_timeout: float = 5.0,
        connection_strategy: ConnectionStrategy | None = None,
    ) -> None:
        super().__init__()
        self.members = list(members)
        self.transport = transport
        self.session_timeout = session_timeout
        self.strategy = connection_strategy or AnyConnectionStrategy()
        self.client_id = f"client-{uuid.uuid4().hex[:8]}-{next(_client_counter)}"
        # Observability: submit->response latency, retry/re-route and
        # indeterminate-outcome counters (docs/OBSERVABILITY.md). The
        # hot path pays one counter add and, per flushed batch, one
        # histogram record.
        self.metrics = MetricsRegistry()

        self._client = transport.client()
        self._loop: asyncio.AbstractEventLoop | None = None  # pinned at open
        self._connection: Connection | None = None
        self._connected_to: Address | None = None
        self._leader_hint: Address | None = None
        self._session = ClientSession(self)
        self._command_seq = 0
        # Exactly-once bookkeeping: the server may prune its response cache
        # only up to the CONTIGUOUS prefix of completed seqs — a higher seq
        # completing first must not ack a lower seq still being retried.
        self._completed_seqs: set[int] = set()
        self._acked_command_seq = 0
        # High-water applied index seen, per Raft group (sequential
        # consistency). Single-group servers live entirely in key 0 —
        # the legacy scalar; a multi-group server (RegisterResponse
        # ``groups`` > 1) tags response indices with the owning group
        # (``index * G + g``) and reads the whole dict on queries.
        self._indices: dict[int, int] = {}
        self._num_groups = 1
        self._keepalive: Scheduled | None = None
        # Command micro-batching: same-turn submits coalesce into ONE
        # CommandBatchRequest (flushed via call_soon at the end of the
        # event-loop turn); a lone submit still rides CommandRequest.
        self._pending_batch: list = []
        self._batch_scheduled = False
        # Query micro-batching: same-turn reads bucket by consistency
        # level (the server's gate differs per level) and ride one
        # QueryBatchRequest — the linearizable gate's quorum round is
        # amortized over the whole batch.
        self._pending_queries: dict[str, list] = {}
        self._query_flush_scheduled = False
        # Follower read scale-out: SEQUENTIAL/CAUSAL reads round-robin
        # across ALL members instead of pinning the session connection
        # (usually the leader) — any server may serve them at or after
        # the client's index (the server-side client-index wait), so
        # read throughput scales with replicas. Leader fallback on lag
        # refusal / unreachable follower. COPYCAT_CLIENT_FOLLOWER_READS=0
        # restores leader-pinned reads (the scale-out A/B knob).
        self._follower_reads = knobs.get_bool("COPYCAT_CLIENT_FOLLOWER_READS")
        self._read_connections: dict[Address, Connection] = {}
        self._read_rr = 0
        # Edge read tier (docs/EDGE_READS.md): client-local CRDT
        # replicas serving CAUSAL/SEQUENTIAL reads without a server
        # hop, fed by per-resource deltas over the session event
        # channel. COPYCAT_EDGE_READS=0 removes the tier entirely — no
        # replica, no subscribe fields, the server-read plane
        # bit-identically (the A/B discipline).
        self._edge = None
        if knobs.get_bool("COPYCAT_EDGE_READS"):
            from .edge import EdgeReadTier
            self._edge = EdgeReadTier(self)

    # -- lifecycle ---------------------------------------------------------

    def session(self) -> ClientSession:
        return self._session

    @property
    def index(self) -> int:
        return max(self._indices.values(), default=0)

    def _read_index(self) -> Any:
        """The ``index`` field for outgoing reads: the legacy scalar on a
        single-group server, the per-group dict on a multi-group one
        (the server extracts the owning group's entry per routed op)."""
        if self._num_groups == 1:
            return self._indices.get(0, 0)
        return dict(self._indices)

    def _note_index(self, value: Any) -> None:
        """Fold a response index into the per-group high-water map:
        scalars are group-0 (single-group) or group-tagged
        (``idx * G + g``, multi-group); dicts are per-group maps
        (multi-group query batches)."""
        if not value:
            return
        if isinstance(value, dict):
            for g, idx in value.items():
                g = int(g)
                if idx and idx > self._indices.get(g, 0):
                    self._indices[g] = idx
            return
        if self._num_groups > 1:
            g = value % self._num_groups
            idx = value // self._num_groups
        else:
            g, idx = 0, value
        if idx > self._indices.get(g, 0):
            self._indices[g] = idx

    async def _do_open(self) -> None:
        self._loop = asyncio.get_running_loop()
        await self._register()
        interval = max(self._session.timeout / 4.0, 0.05)
        self._keepalive = Scheduled(interval, interval, self._send_keepalive)

    async def _do_close(self) -> None:
        if self._keepalive is not None:
            self._keepalive.cancel()
            self._keepalive = None
        if self._session.is_open and self._session.id is not None:
            try:
                response = await self._request(
                    msg.UnregisterRequest(session_id=self._session.id))
            except (TransportError, OSError, msg.ProtocolError, asyncio.TimeoutError):
                pass
        self._session._closed()
        await self._client.close()
        self._connection = None
        self._read_connections.clear()

    # -- connection management --------------------------------------------

    async def _connect(self) -> Connection:
        if self._connection is not None and not self._connection.closed:
            return self._connection
        candidates: list[Address] = []
        if self._leader_hint is not None:
            candidates.append(self._leader_hint)
        candidates += [a for a in self.strategy.order(self.members) if a not in candidates]
        last_error: Exception | None = None
        for address in candidates:
            try:
                conn = await self._client.connect(address)
            except (TransportError, OSError) as e:
                last_error = e
                continue
            conn.handler(msg.PublishRequest, self._on_publish)
            self._connection = conn
            self._connected_to = address
            return conn
        raise TransportError(f"no reachable server in {self.members}") from last_error

    def _drop_connection(self) -> None:
        conn = self._connection
        self._connection = None
        self._connected_to = None
        if conn is not None and not conn.closed:
            spawn(conn.close(), name="drop-connection")

    async def _request(self, request: Any, leader_required: bool = True,
                       attempts: int = 30,
                       per_try_timeout: float | None = None) -> Any:
        """Send with retry/re-route until a non-routing error or success.

        ``per_try_timeout`` bounds ONE attempt (default: the session
        timeout). Keep-alives pass a fraction of it: an attempt stuck at
        a stale leader (appended, never committable) otherwise burns the
        whole session budget before re-routing — the session then
        expires at the real leader even though the majority was
        reachable all along (found by the partition nemesis once
        new-leader expiry actually worked)."""
        backoff = 0.01
        last: Exception | None = None
        tmo = per_try_timeout if per_try_timeout is not None \
            else self.session_timeout
        for _ in range(attempts):
            try:
                conn = await self._connect()
                response = await asyncio.wait_for(conn.send(request), tmo)
            except (TransportError, OSError, asyncio.TimeoutError) as e:
                last = e
                self.metrics.counter("client_retries").inc()
                # A hinted leader that failed the attempt gets no second
                # pin: _connect prefers the hint, so keeping it after a
                # timeout re-dialed the SAME stuck server every retry —
                # under a partitioned-but-dialable old leader the client
                # never reached the majority side (found by the
                # partition nemesis, tests/test_nemesis_raft.py).
                if self._connected_to is not None \
                        and self._connected_to == self._leader_hint:
                    self._leader_hint = None
                self._drop_connection()
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 0.25)
                continue
            error = getattr(response, "error", None)
            if error in (msg.NOT_LEADER, msg.NO_LEADER):
                self.metrics.counter("client_reroutes").inc()
                self._leader_hint = getattr(response, "leader", None)
                members = getattr(response, "members", None)
                if members:
                    self.members = list(members)
                if leader_required or self._leader_hint is None:
                    self._drop_connection()
                    await asyncio.sleep(backoff)
                    backoff = min(backoff * 2, 0.25)
                    continue
            return response
        raise msg.ProtocolError(msg.NO_LEADER, f"no leader after retries: {last}")

    async def _request_read(self, request: Any) -> Any:
        """Send one SEQUENTIAL/CAUSAL read to the next server round-robin
        (followers included — they serve at or after the client's index
        via the server-side applied wait), falling back to the routed
        leader path when a follower is unreachable, lagging behind the
        client's index, or refuses to serve. Read connections are cached
        separately from the session connection so follower reads never
        steal the event/command channel."""
        members = list(self.members)
        count = len(members)
        for _ in range(count):
            address = members[self._read_rr % count]
            self._read_rr += 1
            conn = self._read_connections.get(address)
            if conn is None or conn.closed:
                try:
                    conn = await self._client.connect(address)
                except (TransportError, OSError):
                    continue
                self._read_connections[address] = conn
            try:
                response = await asyncio.wait_for(
                    conn.send(request), self.session_timeout)
            except (TransportError, OSError, asyncio.TimeoutError):
                self._read_connections.pop(address, None)
                if not conn.closed:
                    spawn(conn.close(), name="drop-read-connection")
                continue
            error = getattr(response, "error", None)
            if error in (msg.NOT_LEADER, msg.NO_LEADER, msg.INTERNAL):
                # lag refusal ("state lagging behind client index") or a
                # server that won't serve: take the leader-routed path
                break
            self.metrics.counter("client_reads_follower_lane").inc()
            return response
        self.metrics.counter("client_reads_leader_fallback").inc()
        return await self._request(request, leader_required=False)

    # -- session protocol --------------------------------------------------

    async def _register(self) -> None:
        response = await self._request(msg.RegisterRequest(
            client_id=self.client_id, timeout=self.session_timeout))
        response.raise_if_error()
        self._session.id = response.session_id
        self._session.timeout = response.timeout or self.session_timeout
        if response.members:
            self.members = list(response.members)
        # multi-group server (docs/SHARDING.md): switch on per-group
        # read indices + event channels for this session's lifetime
        self._num_groups = max(1, getattr(response, "groups", None) or 1)
        self._session._opened()

    async def _send_keepalive(self) -> None:
        if not self._session.is_open:
            return
        unsub = (self._edge.take_unsubscribes()
                 if self._edge is not None else None)
        try:
            session = self._session
            event_index: Any = (session.event_index
                                if self._num_groups == 1
                                else dict(session._event_indices))
            response = await self._request(
                msg.KeepAliveRequest(
                    session_id=session.id,
                    command_seq=self._acked_command_seq,
                    event_index=event_index,
                    unsubscribe=unsub),
                # timeout/4 = the keep-alive interval: a stuck attempt
                # yields to the next tick's re-route, and the floor
                # keeps slow-but-healthy commits (hundreds of ms) from
                # spuriously dropping the shared connection
                per_try_timeout=max(1.0, self._session.timeout / 4.0))
        except (msg.ProtocolError, TransportError, OSError, asyncio.TimeoutError):
            if self._edge is not None:
                # retiring a subscription is idempotent: re-stage for
                # the next tick instead of leaking the registry entry
                self._edge.restage_unsubscribes(unsub)
            return
        if response.error == msg.UNKNOWN_SESSION:
            self._session._expired()
        elif response.ok and response.members:
            self.members = list(response.members)

    async def _on_publish(self, request: msg.PublishRequest) -> msg.PublishResponse:
        session = self._session
        trace = getattr(request, "trace", None)
        t0 = time.perf_counter() if trace is not None else 0.0
        # the event channel is per group on a multi-group server (the
        # response's event_index is the position on THAT group's channel)
        g = getattr(request, "group", None) or 0
        position = session._event_indices.get(g, 0)
        if request.session_id != session.id:
            return msg.PublishResponse(event_index=position)
        deltas = getattr(request, "deltas", None)
        if deltas and self._edge is not None:
            # edge state deltas (docs/EDGE_READS.md): merged BEFORE the
            # event-channel gap check — the CRDT merge needs no position
            self._edge.ingest(deltas, trace)
        if request.event_index is None:
            # delta-only push: the event channel's position is untouched
            return msg.PublishResponse(event_index=position)
        if request.prev_event_index != position:
            # Gap or replay: report our position; the server resends from there.
            return msg.PublishResponse(event_index=position)
        for event, message in request.events or []:
            try:
                session._dispatch(event, message)
            except Exception:  # listener errors must not poison the channel
                pass
        session._event_indices[g] = request.event_index
        if trace is not None:
            # traced event delivery: receipt + listener dispatch on the
            # originating causal timeline (member tag "client")
            TRACER.span(trace, "client.event", t0, time.perf_counter(),
                        group=g, n=len(request.events or ()))
        return msg.PublishResponse(event_index=request.event_index)

    # -- operation submission ---------------------------------------------

    async def submit(self, operation: Operation) -> Any:
        if isinstance(operation, Query):
            return await self._submit_query(operation)
        return await self._submit_command(operation)

    def submit_command_nowait(self, operation: Command) -> "asyncio.Future":
        """Stage one command into the current micro-batch and return its
        future directly (no coroutine frame). The awaitable-returning hot
        path: resource facades flatten their submit chain through this,
        cutting ~4 async frames per op off the public SPI plane."""
        if not self._session.is_open:
            raise SessionExpiredError("session is not open")
        self._command_seq += 1
        seq = self._command_seq
        loop = self._loop  # pinned at open: one lookup per op saved
        fut: asyncio.Future = loop.create_future()
        self._pending_batch.append((seq, operation, fut))
        if not self._batch_scheduled:
            self._batch_scheduled = True
            loop.call_soon(self._launch_batch)
        return fut

    async def _submit_command(self, operation: Command) -> Any:
        return await self.submit_command_nowait(operation)

    def _launch_batch(self) -> None:
        self._batch_scheduled = False
        batch, self._pending_batch = self._pending_batch, []
        if batch:
            spawn(self._flush_batch(batch), name="command-batch")

    def _submit_done(self, t0: float, n: int, trace: int | None) -> None:
        """Per-request latency bookkeeping: one histogram sample per wire
        request (every command in a batch experienced that latency), one
        ``client.submit`` span when tracing."""
        end = time.perf_counter()
        self.metrics.histogram("submit_latency_ms").record((end - t0) * 1e3)
        if trace is not None:
            TRACER.span(trace, "client.submit", t0, end, n=n)

    def _submit_failed(self, e: BaseException, n: int) -> None:
        """A submit whose outcome is UNKNOWN is INDETERMINATE — the
        reference's session-loss command failure. That is exactly the
        routing-exhaustion ProtocolError from ``_request`` (per-attempt
        timeouts are retried internally and surface as NO_LEADER; the
        command may have been appended by a leader we lost)."""
        if isinstance(e, msg.ProtocolError) \
                and e.code in (msg.NO_LEADER, msg.NOT_LEADER):
            self.metrics.counter("commands_indeterminate").inc(n)

    async def _flush_batch(self, batch: list) -> None:
        self.metrics.counter("commands_submitted").inc(len(batch))
        trace = TRACER.new_trace() if TRACER.enabled else None
        t0 = time.perf_counter()
        if len(batch) == 1:
            seq, operation, fut = batch[0]
            try:
                response = await self._request(msg.CommandRequest(
                    session_id=self._session.id, seq=seq,
                    operation=operation, trace=trace))
                result = self._finish(response, seq)
            except BaseException as e:  # noqa: BLE001 — delivered via fut
                self._submit_failed(e, 1)
                if not fut.done():
                    fut.set_exception(e)
                return
            self._submit_done(t0, 1, trace)
            if not fut.done():
                fut.set_result(result)
            return
        try:
            response = await self._request(msg.CommandBatchRequest(
                session_id=self._session.id,
                entries=[(seq, op) for seq, op, _ in batch], trace=trace))
            # batch-level fatal (UNKNOWN_SESSION etc.): _finish raises
            # the right exception type for every entry
            if getattr(response, "error", None):
                self._finish(response, None)
        except BaseException as e:  # noqa: BLE001
            self._submit_failed(e, len(batch))
            for _, _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)
            return
        self._submit_done(t0, len(batch), trace)
        resp_entries = response.entries or []
        # positional fast path: the server answers in request order, so
        # the common case correlates by zip — the by-seq dict is built
        # only when shapes/seqs disagree (partial or reordered response).
        # The seq comparison runs as two listcomps + one C-level list
        # compare (measurably cheaper than a per-pair generator walk).
        if len(resp_entries) == len(batch) and \
                [e[0] for e in resp_entries] == [b[0] for b in batch]:
            paired = zip(batch, resp_entries)
        else:
            by_seq = {entry[0]: entry for entry in resp_entries}
            paired = ((b, by_seq.get(b[0])) for b in batch)
        try:
            for (seq, _, fut), entry in paired:
                if entry is None:
                    if not fut.done():
                        fut.set_exception(msg.ProtocolError(
                            msg.INTERNAL,
                            f"seq {seq} missing from batch response"))
                    continue
                _, index, result, code, detail = entry
                # ack BEFORE consulting fut.done(): a caller-cancelled
                # command that succeeded server-side must still advance
                # the contiguous ack prefix, or server response-cache
                # pruning stalls behind it forever
                if code in (None, msg.APPLICATION):
                    self._ack_seq(seq, index)
                if fut.done():
                    continue
                if code == msg.APPLICATION:
                    fut.set_exception(
                        ApplicationError(detail or "application error"))
                elif code:
                    fut.set_exception(msg.ProtocolError(code, detail or ""))
                else:
                    fut.set_result(result)
        except BaseException as e:  # noqa: BLE001 — no caller may hang
            for _, _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)
            raise

    def _ack_seq(self, seq: int, index: int | None) -> None:
        """Per-command success bookkeeping (the _finish tail): advance the
        sequential-read index and the contiguous completed-seq prefix the
        keep-alive acks for server response-cache pruning."""
        self._note_index(index)
        # in-order completion (every batch entry in a healthy run): just
        # bump the prefix — the out-of-order set stays untouched/empty
        if seq == self._acked_command_seq + 1 and not self._completed_seqs:
            self._acked_command_seq = seq
            return
        self._completed_seqs.add(seq)
        while self._acked_command_seq + 1 in self._completed_seqs:
            self._acked_command_seq += 1
            self._completed_seqs.discard(self._acked_command_seq)

    async def _submit_query(self, operation: Query) -> Any:
        if not self._session.is_open:
            raise SessionExpiredError("session is not open")
        self.metrics.counter("queries_submitted").inc()
        consistency = operation.consistency().value
        edge = self._edge
        if edge is not None and consistency not in (
                "linearizable", "bounded_linearizable"):
            # edge fast path (docs/EDGE_READS.md): a warm replica
            # serves SYNCHRONOUSLY — no future, no micro-batch flush,
            # no wire round-trip; misses fall through to the staged
            # server path (which subscribes + seeds)
            result = edge.try_serve(operation)
            if result is not edge.MISS:
                return result
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._pending_queries.setdefault(consistency, []).append(
            (operation, fut))
        if not self._query_flush_scheduled:
            self._query_flush_scheduled = True
            loop.call_soon(self._launch_query_batches)
        return await fut

    def _launch_query_batches(self) -> None:
        self._query_flush_scheduled = False
        pending, self._pending_queries = self._pending_queries, {}
        for consistency, items in pending.items():
            if items:
                spawn(self._flush_query_batch(consistency, items),
                      name="query-batch")

    async def _flush_query_batch(self, consistency: str,
                                 items: list) -> None:
        leader_required = consistency in ("linearizable",
                                          "bounded_linearizable")
        # Edge read tier (docs/EDGE_READS.md): these reads already
        # missed the replica (the fast path in _submit_query serves
        # hits synchronously) — edge-shaped misses carry the
        # `subscribe` flag and route over the SESSION connection (the
        # member that pushes this session's deltas), so the response
        # seeds the replica and later reads stay local.
        edge = self._edge if not leader_required else None
        subscribe = (1 if edge is not None and edge.wants_subscribe(items)
                     else None)
        # every read is tagged with its consistency (the request field);
        # sub-linearizable levels route round-robin across replicas
        # (subscribing reads excepted — deltas flow over the session
        # connection, so the subscription must land on its holder)
        round_robin = (not leader_required and self._follower_reads
                       and subscribe is None and len(self.members) > 1)
        if len(items) == 1:
            operation, fut = items[0]
            request = msg.QueryRequest(
                session_id=self._session.id, index=self._read_index(),
                operation=operation, consistency=consistency,
                subscribe=subscribe)
            try:
                if round_robin:
                    response = await self._request_read(request)
                else:
                    response = await self._request(
                        request, leader_required=leader_required)
                result = self._finish(response, None)
            except BaseException as e:  # noqa: BLE001 — delivered via fut
                if not fut.done():
                    fut.set_exception(e)
                return
            if subscribe is not None and edge is not None:
                edge.seed_response(items, getattr(response, "edge", None))
            if not fut.done():
                fut.set_result(result)
            return
        try:
            request = msg.QueryBatchRequest(
                session_id=self._session.id, index=self._read_index(),
                consistency=consistency,
                operations=[op for op, _ in items],
                subscribe=subscribe)
            if round_robin:
                response = await self._request_read(request)
            else:
                response = await self._request(
                    request, leader_required=leader_required)
            if getattr(response, "error", None):
                self._finish(response, None)  # raises the right exception
        except BaseException as e:  # noqa: BLE001
            for _, fut in items:
                if not fut.done():
                    fut.set_exception(e)
            return
        if subscribe is not None and edge is not None:
            edge.seed_response(items, getattr(response, "edge", None))
        try:
            self._note_index(response.index)
            entries = response.entries or []
            for k, (operation, fut) in enumerate(items):
                if fut.done():
                    continue
                if k >= len(entries):
                    fut.set_exception(msg.ProtocolError(
                        msg.INTERNAL, "missing batch query entry"))
                    continue
                result, code, detail = entries[k]
                if code == msg.APPLICATION:
                    fut.set_exception(
                        ApplicationError(detail or "application error"))
                elif code:
                    fut.set_exception(msg.ProtocolError(code, detail or ""))
                else:
                    fut.set_result(result)
        except BaseException as e:  # noqa: BLE001 — no caller may hang
            for _, fut in items:
                if not fut.done():
                    fut.set_exception(e)
            raise

    def _finish(self, response: Any, seq: int | None) -> Any:
        error = getattr(response, "error", None)
        if error == msg.UNKNOWN_SESSION:
            self._session._expired()
            raise SessionExpiredError("session expired")
        if error == msg.APPLICATION:
            if seq is not None:
                # an application error IS a delivered response: ack the
                # seq or the contiguous ack prefix (and server response-
                # cache pruning) would stall behind it forever
                self._ack_seq(seq, getattr(response, "index", None))
            raise ApplicationError(response.error_detail or "application error")
        response.raise_if_error()
        if seq is not None:
            self._ack_seq(seq, response.index)
        else:
            self._note_index(getattr(response, "index", None))
        return response.result
