"""Raft client runtime (Copycat ``CopycatClient``/``RaftClient`` equivalent)."""

from .client import (
    AnyConnectionStrategy,
    ApplicationError,
    ClientSession,
    ConnectionStrategy,
    PinnedConnectionStrategy,
    RaftClient,
    SessionExpiredError,
)

__all__ = [
    "RaftClient",
    "ClientSession",
    "ConnectionStrategy",
    "AnyConnectionStrategy",
    "PinnedConnectionStrategy",
    "ApplicationError",
    "SessionExpiredError",
]
