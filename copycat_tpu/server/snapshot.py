"""Atomic, CRC-framed state-machine snapshot files (the crash-recovery plane).

The reference has no snapshots — durability is "retained commits + replay"
(SURVEY.md §5.4), so a long-lived member replays its whole log to boot and
compaction can never release a segment a peer might still need.  This store
is the durable half of the fix (docs/DURABILITY.md): the server serializes
its state machines + session plane at ``last_applied`` into one payload,
and this module owns the file discipline —

- **atomic**: payload is written to a ``.tmp`` sibling, fsynced, then
  ``os.replace``d into place (a crash never leaves a half-written ``.snap``
  visible under the final name);
- **CRC-framed**: ``[magic][u64 len][u32 crc32(payload, seed)][payload]``,
  same seeded-CRC discipline as the mapped log segments (``log.py``) so an
  all-zero torn file can never validate;
- **self-healing reads**: ``newest()`` walks snapshots newest-first and
  skips any file that fails the frame check — a corrupt newest snapshot
  falls back to the previous one (or to full replay when none survive),
  never to a crash at boot.

File name carries the applied index (``{name}-{index:016d}.snap``) so
ordering is lexicographic and the install plane can serve "the newest
snapshot" without opening every file.
"""

from __future__ import annotations

import logging
import os
import zlib

logger = logging.getLogger(__name__)

#: Frame magic + format version; bump the digit when the payload schema
#: changes incompatibly so old files fail loudly instead of misparsing.
MAGIC = b"CCSNAP1\n"
#: Nonzero CRC seed (same rationale as ``_MappedSegment.CRC_SEED``):
#: crc32(b"") == 0, so with a zero seed an all-zero torn file would
#: validate as an empty payload.
CRC_SEED = 0x5A9C
_HEADER = len(MAGIC) + 8 + 4


def frame(payload: bytes) -> bytes:
    """CRC-frame one snapshot payload."""
    return (MAGIC + len(payload).to_bytes(8, "little")
            + zlib.crc32(payload, CRC_SEED).to_bytes(4, "little") + payload)


def unframe(data: bytes) -> bytes | None:
    """Payload of a framed snapshot, or ``None`` when the frame is torn,
    truncated, or corrupt (bad magic / short payload / CRC mismatch)."""
    if len(data) < _HEADER or not data.startswith(MAGIC):
        return None
    length = int.from_bytes(data[len(MAGIC):len(MAGIC) + 8], "little")
    crc = int.from_bytes(data[len(MAGIC) + 8:_HEADER], "little")
    payload = data[_HEADER:_HEADER + length]
    if len(payload) < length or zlib.crc32(payload, CRC_SEED) != crc:
        return None
    return payload


def fsync_dir(directory: str) -> None:
    """Best-effort directory fsync so a rename survives power loss (not
    all platforms/filesystems allow opening a directory for sync)."""
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:  # pragma: no cover - platform-dependent
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover - platform-dependent
        pass
    finally:
        os.close(fd)


def write_atomic(path: str, data: bytes) -> None:
    """tmp + fsync + atomic rename: the file at ``path`` is either the old
    content or the complete new content, never a torn mix."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path) or ".")


class SnapshotStore:
    """Snapshot files of one server under its storage directory."""

    def __init__(self, directory: str, name: str) -> None:
        self.directory = directory
        self.name = name
        os.makedirs(directory, exist_ok=True)
        #: Snapshots skipped by ``newest()`` for failing the frame check
        #: since this store opened (surfaced as ``snap.bad_crc_skipped``).
        self.bad_skipped = 0

    def _path(self, index: int) -> str:
        return os.path.join(self.directory, f"{self.name}-{index:016d}.snap")

    def indexes(self) -> list[int]:
        """Applied indexes of all snapshot files, ascending."""
        out = []
        prefix = f"{self.name}-"
        for fname in os.listdir(self.directory):
            if fname.startswith(prefix) and fname.endswith(".snap"):
                try:
                    out.append(int(fname[len(prefix):-len(".snap")]))
                except ValueError:
                    continue
        return sorted(out)

    def save(self, index: int, payload: bytes) -> str:
        """Persist one snapshot payload atomically; returns its path."""
        path = self._path(index)
        write_atomic(path, frame(payload))
        return path

    def newest(self) -> tuple[int, bytes] | None:
        """``(index, payload)`` of the newest snapshot that passes the
        frame check; corrupt files are skipped (logged + counted), falling
        back to older snapshots and finally to ``None`` (full replay)."""
        for index in reversed(self.indexes()):
            path = self._path(index)
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                continue
            payload = unframe(data)
            if payload is None:
                self.bad_skipped += 1
                logger.warning(
                    "snapshot %s failed its CRC frame check; skipping "
                    "(falling back to an older snapshot or full replay)",
                    path)
                continue
            return index, payload
        return None

    def gc(self, keep: int = 2) -> int:
        """Delete all but the ``keep`` newest snapshot files; returns the
        number removed. Keeping one spare means a corrupt newest snapshot
        still recovers from the previous one instead of a full replay."""
        removed = 0
        for index in self.indexes()[:-keep if keep else None]:
            try:
                os.remove(self._path(index))
                removed += 1
            except OSError:  # pragma: no cover - racing cleanup
                pass
        return removed
