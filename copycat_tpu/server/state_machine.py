"""State machine SPI (Copycat ``StateMachine``/``StateMachineExecutor``/``Commit``).

Mirrors the consumed surface (SURVEY.md §2.3 "State machine SPI"):

- ``Commit{index, session, time, operation, clean(), close()}``
- ``StateMachineExecutor.register(op_type, fn)`` + reflective auto-registration:
  any public method whose single parameter is annotated ``Commit[SomeOp]`` is
  registered for ``SomeOp`` (the reference's ``*State`` classes never call
  ``register`` themselves — reflection does it, ``ResourceStateMachine.java:33-42``)
- ``StateMachineExecutor.schedule(delay[, interval]) -> Scheduled`` —
  **log-time driven**: deadlines are measured against the replicated logical
  clock (max entry timestamp applied), so TTLs/lock timeouts fire identically
  on every server (SURVEY.md §5.9).  The leader advances the clock by appending
  NoOp entries when a deadline is due; timers only ever fire during ``tick``.
"""

from __future__ import annotations

import heapq
import inspect
import logging
import typing
from typing import Any, Callable, Generic, TypeVar

T = TypeVar("T")


class Commit(Generic[T]):
    """A committed operation handed to a state machine handler."""

    __slots__ = ("index", "session", "time", "operation", "_log", "_cleaned")

    def __init__(self, index: int, session: Any, time: float, operation: T, log: Any = None):
        self.index = index
        self.session = session
        self.time = time
        self.operation = operation
        self._log = log
        self._cleaned = False

    def clean(self) -> None:
        """Mark this commit's effect superseded: the entry may be compacted."""
        if not self._cleaned:
            self._cleaned = True
            if self._log is not None:
                self._log.clean(self.index)

    def close(self) -> None:
        """Release a read-only reference (queries / retained-then-released)."""

    def __repr__(self) -> str:
        return f"Commit(index={self.index}, op={self.operation!r})"


class ScheduledTimer:
    """Deterministic log-time timer handle."""

    __slots__ = ("deadline", "interval", "callback", "cancelled")

    def __init__(self, deadline: float, interval: float | None, callback: Callable[[], None]):
        self.deadline = deadline
        self.interval = interval
        self.callback = callback
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class StateMachineContext:
    """Execution context visible to a state machine during apply."""

    def __init__(self, name: str = "state-machine") -> None:
        self.index = 0  # index of the entry currently being applied
        self.clock = 0.0  # replicated logical time (max entry timestamp)
        self.sessions: dict[int, Any] = {}  # session id -> ServerSession
        self.logger = logging.getLogger(name)


class StateMachineExecutor:
    """Registers operation callbacks and deterministic timers for one machine."""

    def __init__(self, context: StateMachineContext | None = None, log: Any = None) -> None:
        self._context = context or StateMachineContext()
        self._log = log
        self._callbacks: dict[type, Callable[[Commit], Any]] = {}
        self._timers: list[tuple[float, int, ScheduledTimer]] = []
        self._timer_seq = 0

    @property
    def context(self) -> StateMachineContext:
        return self._context

    def logger(self) -> logging.Logger:
        return self._context.logger

    # -- operation registry ------------------------------------------------

    def register(self, op_type: type, callback: Callable[[Commit], Any]) -> "StateMachineExecutor":
        self._callbacks[op_type] = callback
        return self

    def rewrap(self, wrapper: Callable[[Callable], Callable]) -> None:
        """Rewrite every registered callback through ``wrapper`` (the
        device executor wraps generator handlers into batchable jobs)."""
        self._callbacks = {t: wrapper(fn) for t, fn in self._callbacks.items()}

    def callback_for(self, op_type: type) -> Callable[[Commit], Any] | None:
        for cls in op_type.__mro__:
            fn = self._callbacks.get(cls)
            if fn is not None:
                return fn
        return None

    def execute(self, commit: Commit) -> Any:
        fn = self.callback_for(type(commit.operation))
        if fn is None:
            raise ValueError(f"no handler registered for {type(commit.operation).__name__}")
        return fn(commit)

    # -- deterministic timers ---------------------------------------------

    def schedule(
        self, delay: float, callback: Callable[[], None], interval: float | None = None
    ) -> ScheduledTimer:
        timer = ScheduledTimer(self._context.clock + delay, interval, callback)
        self._timer_seq += 1
        heapq.heappush(self._timers, (timer.deadline, self._timer_seq, timer))
        return timer

    def next_deadline(self) -> float | None:
        while self._timers and self._timers[0][2].cancelled:
            heapq.heappop(self._timers)
        return self._timers[0][0] if self._timers else None

    def tick(self, timestamp: float) -> None:
        """Fire all timers with deadline <= timestamp, in deadline order."""
        while self._timers and self._timers[0][0] <= timestamp:
            _, _, timer = heapq.heappop(self._timers)
            if timer.cancelled:
                continue
            try:
                timer.callback()
            except Exception:
                self._context.logger.exception("state machine timer failed")
            if timer.interval is not None and not timer.cancelled:
                timer.deadline += timer.interval
                self._timer_seq += 1
                heapq.heappush(self._timers, (timer.deadline, self._timer_seq, timer))

    def close(self) -> None:
        for _, _, timer in self._timers:
            timer.cancel()
        self._timers.clear()


#: class -> [(method name, Commit[Op] type)] — see _auto_register
_AUTO_REG_TABLES: dict[type, list] = {}


class StateMachine:
    """Base replicated state machine.

    Subclass and either annotate single-parameter methods with ``Commit[Op]``
    (auto-registered, mirroring the reference's reflection) or override
    ``configure`` and call ``executor.register`` explicitly.
    """

    def __init__(self) -> None:
        self.executor: StateMachineExecutor | None = None

    # -- lifecycle ---------------------------------------------------------

    def init(self, executor: StateMachineExecutor) -> None:
        self.executor = executor
        self.configure(executor)
        self._auto_register(executor)

    def configure(self, executor: StateMachineExecutor) -> None:
        """Hook for explicit operation registration."""

    # -- keyspace sharding hook (docs/SHARDING.md) ------------------------

    @classmethod
    def route_group(cls, operation: Any, groups: int) -> int:
        """The Raft group owning ``operation`` on a multi-group server.

        Must be a pure function of the operation and the group count —
        identical on every member and across restarts (the hash-routing
        stability contract). The default pins everything to group 0;
        machines that shard (ResourceManager, bench fixtures) override
        with a stable key hash."""
        return 0

    def _auto_register(self, executor: StateMachineExecutor) -> None:
        # The (method name -> Commit[Op] type) table is a pure function of
        # the CLASS; the signature/type-hint introspection below is
        # expensive (the SPI profile showed ~10% of server wall time spent
        # re-deriving it once per resource INSTANCE at 1k instances), so
        # it is computed once per class and memoized.
        table = _AUTO_REG_TABLES.get(type(self))
        if table is None:
            table = []
            for name in dir(self):
                if name.startswith("_"):
                    continue
                method = getattr(self, name)
                if not inspect.ismethod(method):
                    continue
                try:
                    params = list(
                        inspect.signature(method).parameters.values())
                except (TypeError, ValueError):  # pragma: no cover
                    continue
                if len(params) != 1:
                    continue
                op_type = _commit_op_type(method, params[0])
                if op_type is not None:
                    table.append((name, op_type))
            _AUTO_REG_TABLES[type(self)] = table
        for name, op_type in table:
            if executor.callback_for(op_type) is None:
                executor.register(op_type, getattr(self, name))

    # -- snapshot hooks (crash-recovery plane, docs/DURABILITY.md) --------

    def snapshot_state(self) -> Any:
        """Serializer-writable image of this machine's replicated state at
        the current applied index, or ``NotImplemented`` (the default) when
        the machine cannot be snapshotted — the server then skips snapshot
        capture entirely rather than persist a lossy image.

        Contract for implementers: the returned object must round-trip
        through ``io.serializer.Serializer`` (primitives, containers,
        bytes, registered classes), and machines owning log-time timers
        must include enough information to RE-SCHEDULE them in
        :meth:`restore_state` (deadlines are absolute log-clock values;
        re-schedule with ``deadline - context.clock``)."""
        return NotImplemented

    def restore_state(self, data: Any, sessions: dict[int, Any]) -> None:
        """Rebuild replicated state from a :meth:`snapshot_state` image.
        ``sessions`` is the restored session table (id -> ServerSession) so
        machines tracking sessions can re-bind them by id."""

    # -- session lifecycle hooks (SURVEY.md §3.4) -------------------------

    def register(self, session: Any) -> None:
        """A session opened against this machine."""

    def expire(self, session: Any) -> None:
        """A session timed out (crash suspected) — deterministic on all servers."""

    def close(self, session: Any) -> None:
        """A session closed (gracefully or after expiry)."""


def _commit_op_type(method: Callable, param: inspect.Parameter) -> type | None:
    """Extract ``X`` from a parameter annotated ``Commit[X]``."""
    annotation = param.annotation
    if annotation is inspect.Parameter.empty:
        return None
    if isinstance(annotation, str):
        try:
            hints = typing.get_type_hints(method)
        except Exception:
            return None
        annotation = hints.get(param.name, None)
        if annotation is None:
            return None
    origin = typing.get_origin(annotation)
    if origin is Commit:
        args = typing.get_args(annotation)
        if len(args) == 1 and isinstance(args[0], type):
            return args[0]
    return None
