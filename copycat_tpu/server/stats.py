"""Opt-in live stats surface: an HTTP listener over a running server.

The observability plane's exposition layer (docs/OBSERVABILITY.md): a
tiny dependency-free HTTP/1.1 responder on asyncio streams (the stats
port must work even when the cluster transport is LocalTransport or the
native loop — it is always a real TCP socket, so ``curl`` and Prometheus
can scrape a test cluster).

Routes:

- ``/stats`` (also ``/`` and ``/stats.json``) — the full JSON snapshot
  (``RaftServer.stats_snapshot()``: node/role/term/leader + raft,
  transport and manager registries).
- ``/metrics`` — Prometheus text exposition: the raft registry under
  ``copycat_*``, the transport's under ``copycat_transport_*``, the
  resource manager's under ``copycat_manager_*``.
- ``/health`` — the health plane's verdict (``utils/health.py``): a
  fresh detector evaluation — status/reasons/per-group breakdown with
  the evidence series attached; ``{"status": "disabled"}`` under
  ``COPYCAT_HEALTH=0``.
- ``/healthz`` — minimal liveness: 200 + role/term only, no snapshot
  cost — safe for high-frequency probes.
- ``/traces`` — JSON dump of the slowest traced requests
  (``utils/tracing.py``); ``/traces.txt`` for the human rendering.
- ``/traces/<id>`` — THIS member's spans for one trace id: the
  collection route ``copycat-tpu trace`` fans out across members to
  assemble the cross-member causal waterfall.
- ``/flight`` — the device-plane flight recorder (telemetry spikes,
  injected faults, invariant violations in one fault-correlated ring —
  ``models/telemetry.py``); ``/flight.txt`` for the human rendering.
  Active when the server runs the TPU executor with telemetry on
  (``COPYCAT_TELEMETRY=1`` / ``DeviceEngineConfig(telemetry=True)``).
  With the health plane on, also carries the durable black-box
  (``utils/health.py``): the previous life's events reloaded at boot
  and tagged ``recovered=true`` — what post-SIGKILL forensics read.
- ``/series`` — the retrospective-telemetry ring
  (``utils/timeseries.py``): the host's retained metric samples,
  windowable with ``?since=<wall seconds>`` and filterable with
  ``?names=<prefix,prefix>``; ``/series.txt`` renders sparklines.
  Served by every process role (member, ingress, supervisor) — what
  ``copycat-tpu timeline`` merges. Absent under ``COPYCAT_SERIES=0``
  (the pre-series surface, bit-identical).

Enable with ``AtomixServer(..., stats_port=N)`` /
``copycat-server --stats-port N``; read with ``copycat-tpu stats
<host:port>`` or anything that speaks HTTP.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any

from ..utils.buildinfo import healthz_identity
from ..utils.metrics import MetricsRegistry
from ..utils.tracing import TRACER

logger = logging.getLogger(__name__)


def _series_query(query: str) -> tuple[float | None, list[str] | None]:
    """Parse ``?since=<wall seconds>&names=<prefix,prefix>`` for the
    ``/series`` routes; malformed values degrade to the unfiltered
    window rather than a 500 (observability never wounds)."""
    since: float | None = None
    names: list[str] | None = None
    for part in query.split("&"):
        key, _, value = part.partition("=")
        if key == "since" and value:
            try:
                since = float(value)
            except ValueError:
                pass
        elif key == "names" and value:
            names = [n for n in value.split(",") if n]
    return since, names


def _profile_query(query: str) -> tuple[float | None, int | None]:
    """Parse ``?since=<wall seconds>&top=<K>`` for the ``/profile``
    routes; malformed values degrade to the unfiltered window rather
    than a 500 (observability never wounds)."""
    since: float | None = None
    top: int | None = None
    for part in query.split("&"):
        key, _, value = part.partition("=")
        if key == "since" and value:
            try:
                since = float(value)
            except ValueError:
                pass
        elif key == "top" and value:
            try:
                top = max(1, int(value))
            except ValueError:
                pass
    return since, top


class StatsListener:
    """Serves one RaftServer's observability surface over HTTP.

    Binds loopback by default: the surface is unauthenticated (and
    ``/traces`` carries operation metadata), so exposure beyond the
    host is an explicit choice (``--stats-host`` /
    ``with_stats_port(port, host=...)``)."""

    def __init__(self, raft_server: Any, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self._raft = raft_server
        self._host = host
        self._port = port
        self._server: asyncio.AbstractServer | None = None

    @property
    def port(self) -> int:
        """The bound port (resolves ``port=0`` to the ephemeral pick)."""
        if self._server is not None and self._server.sockets:
            return self._server.sockets[0].getsockname()[1]
        return self._port

    async def open(self) -> "StatsListener":
        self._server = await asyncio.start_server(
            self._serve, self._host, self._port)
        logger.info("stats listener on %s:%d", self._host, self.port)
        return self

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2.0)
            except (TimeoutError, asyncio.TimeoutError):
                pass
            self._server = None

    # -- request handling --------------------------------------------------

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            request_line = await asyncio.wait_for(reader.readline(), 5.0)
            parts = request_line.decode("latin-1").split()
            path = parts[1] if len(parts) >= 2 else "/"
            # drain headers (ignored; routes take only query params)
            while True:
                line = await asyncio.wait_for(reader.readline(), 5.0)
                if line in (b"\r\n", b"\n", b""):
                    break
            raw_path, _, query = path.partition("?")
            body, ctype = self._route(raw_path, query)
            writer.write(
                b"HTTP/1.1 200 OK\r\n"
                + f"Content-Type: {ctype}\r\n".encode()
                + f"Content-Length: {len(body)}\r\n".encode()
                + b"Connection: close\r\n\r\n" + body)
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionResetError, OSError,
                asyncio.IncompleteReadError):
            pass
        except Exception:
            logger.exception("stats request failed")
            try:
                writer.write(b"HTTP/1.1 500 Internal Server Error\r\n"
                             b"Content-Length: 0\r\nConnection: close\r\n\r\n")
                await writer.drain()
            except Exception:
                pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    def _route(self, path: str, query: str = "") -> tuple[bytes, str]:
        if path == "/metrics":
            return self._prometheus().encode(), "text/plain; version=0.0.4"
        if path == "/healthz":
            # minimal liveness: role/term only, no snapshot refresh, no
            # registry walk — safe to poll at any frequency (the
            # deployment supervisor's watch cadence). Non-member hosts
            # (the standalone ingress tier) provide their own payload.
            # Every role's payload carries uptime_s + git_sha
            # (utils/buildinfo.py): a restarted or half-rolled child is
            # distinguishable from one that was healthy all along.
            info = getattr(self._raft, "healthz_info", None)
            if callable(info):
                payload = dict(info())
            else:
                g0 = self._raft.groups[0]
                payload = {
                    "ok": True, "node": str(self._raft.address),
                    "role": g0.role, "term": g0.term,
                }
            payload.update(healthz_identity())
            return json.dumps(payload).encode(), "application/json"
        if path == "/health":
            # the health plane's verdict (docs/OBSERVABILITY.md "Health
            # & diagnosis"): rate-limited re-evaluation — at most one
            # fresh tick per half-cadence, so a high-frequency probe
            # cannot flood the evidence windows and shrink every delta
            # detector's lookback (observing health must not suppress it)
            monitor = getattr(self._raft, "health", None)
            if monitor is None:
                body = json.dumps({
                    "status": "disabled",
                    "node": str(self._raft.address),
                    "note": "health plane off (COPYCAT_HEALTH=0)"})
            else:
                body = json.dumps(monitor.verdict())
            return body.encode(), "application/json"
        if path == "/traces":
            return TRACER.dump_slowest(20, as_json=True).encode(), \
                "application/json"
        if path == "/traces.txt":
            return TRACER.dump_slowest(20).encode(), "text/plain"
        if path.startswith("/traces/"):
            # the cross-member collection route: THIS member's spans for
            # one trace id (`copycat-tpu trace` fans this out to every
            # member and assembles the causal waterfall — utils/tracing
            # `assemble_trace`); unknown/evicted ids serve an empty span
            # list, which the assembler marks incomplete, never drops
            try:
                trace_id = int(path.rsplit("/", 1)[1])
            except ValueError:
                return (json.dumps({"error": "trace id must be an int"})
                        .encode(), "application/json")
            spans = [s.as_dict() for s in TRACER.spans_for(trace_id)]
            return (json.dumps({
                "trace": trace_id,
                "member": str(self._raft.address),
                "spans": spans,
            }).encode(), "application/json")
        if path == "/flight":
            # the in-memory ring (when a telemetry-enabled engine runs)
            # PLUS the durable black-box: recovered events from the
            # previous life ride under "blackbox" tagged recovered=true
            # — the post-SIGKILL forensics surface `doctor` correlates
            hub = self._device_hub()
            payload: dict = {"events": (hub.flight.events()
                                        if hub is not None else [])}
            if hub is None:
                payload["note"] = ("device-plane telemetry disabled "
                                   "(COPYCAT_TELEMETRY=1 or "
                                   "DeviceEngineConfig(telemetry=True))")
            blackbox = getattr(self._raft, "blackbox", None)
            if blackbox is not None:
                payload["blackbox"] = {
                    **blackbox.summary(),
                    "recovered": blackbox.recovered,
                    "events": blackbox.events(),
                }
            return json.dumps(payload).encode(), "application/json"
        if path == "/flight.txt":
            hub = self._device_hub()
            body = (hub.flight.render_text() if hub is not None
                    else "device-plane telemetry disabled\n")
            blackbox = getattr(self._raft, "blackbox", None)
            if blackbox is not None and blackbox.recovered:
                body += (f"--- black-box: {len(blackbox.recovered)} "
                         f"recovered event(s) from the previous life ---\n")
                for ev in blackbox.recovered:
                    extra = " ".join(f"{k}={v}" for k, v in ev.items()
                                     if k not in ("seq", "t", "kind",
                                                  "recovered"))
                    body += (f"#{ev.get('seq', '?'):<5} "
                             f"{ev.get('kind', '?'):<12} {extra}\n")
            return body.encode(), "text/plain"
        store = getattr(self._raft, "series", None)
        if path in ("/series", "/series.txt") and store is not None:
            # the retrospective-telemetry ring (utils/timeseries.py):
            # ?since=<wall s> windows, ?names=<prefix,...> filters —
            # what `copycat-tpu timeline` fans out for. When the plane
            # is off the path falls through to the unknown-route error:
            # /series is ABSENT, not empty (the A/B surface).
            since, names = _series_query(query)
            if path == "/series":
                return (json.dumps(store.payload(since=since, names=names))
                        .encode(), "application/json")
            return (store.render_text(since=since, names=names).encode(),
                    "text/plain")
        prof = getattr(self._raft, "profiler", None)
        if path in ("/profile", "/profile.txt") and prof is not None:
            # the continuous profiling plane (utils/profiler.py):
            # folded wall stacks + loop holds, ?since=<wall s> windows,
            # ?top=<K> truncation — what `copycat-tpu profile` fans out
            # and merges. /profile.txt is pure flamegraph.pl collapsed
            # lines. COPYCAT_PROFILE=0 falls through to the
            # unknown-route error: ABSENT, not empty (the A/B surface).
            since, top = _profile_query(query)
            if path == "/profile":
                payload = prof.payload(since=since, top=top)
                payload["node"] = str(self._raft.address)
                return (json.dumps(payload).encode(), "application/json")
            return (prof.render_text(since=since, top=top).encode(),
                    "text/plain")
        if path in ("/", "/stats", "/stats.json"):
            return json.dumps(self._raft.stats_snapshot()).encode(), \
                "application/json"
        routes = ["/stats", "/metrics", "/health", "/healthz", "/traces",
                  "/traces.txt", "/traces/<id>", "/flight", "/flight.txt"]
        if store is not None:
            routes += ["/series", "/series.txt"]
        if prof is not None:
            routes += ["/profile", "/profile.txt"]
        return (json.dumps({"error": f"unknown path {path}",
                            "routes": routes}).encode(),
                "application/json")

    def _device_hub(self):
        """The device engine's telemetry hub, when the server runs the
        TPU executor with an instantiated, telemetry-enabled engine.
        Reads the raw ``_engine`` attribute — the ``device_engine``
        property builds the engine lazily, and a stats scrape must
        never trigger a multi-second jit compile."""
        engine = getattr(self._raft.state_machine, "_engine", None)
        groups = getattr(engine, "_groups", None)
        return getattr(groups, "telemetry", None)

    def _prometheus(self) -> str:
        self._raft.stats_snapshot()  # refresh the lazy gauges
        out = [self._raft.metrics.render_prometheus()]
        transport_metrics = getattr(self._raft.transport, "metrics", None)
        if isinstance(transport_metrics, MetricsRegistry):
            out.append(transport_metrics.render_prometheus(
                namespace="copycat_transport"))
        manager_metrics = getattr(self._raft.state_machine, "metrics", None)
        if isinstance(manager_metrics, MetricsRegistry):
            out.append(manager_metrics.render_prometheus(
                namespace="copycat_manager"))
        hub = self._device_hub()
        if hub is not None:
            # device.* sanitizes to copycat_device_* — the device-plane
            # family next to the host families in one scrape
            out.append(hub.registry.render_prometheus(namespace="copycat"))
        return "".join(out)


async def fetch_stats(address: str, path: str = "/stats",
                      timeout: float = 5.0) -> bytes:
    """Minimal HTTP GET against a stats listener (no external deps —
    what ``copycat-tpu stats`` uses). ``address`` is ``host:port``."""
    host, _, port = address.rpartition(":")
    if not port.isdigit():
        # a malformed address must be a one-line actionable error at the
        # CLI, not an int() traceback
        raise RuntimeError(
            f"bad address {address!r} — expected host:port (the "
            f"server's --stats-port endpoint)")
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host or "127.0.0.1", int(port)), timeout)
    try:
        writer.write(f"GET {path} HTTP/1.1\r\nHost: {address}\r\n"
                     f"Connection: close\r\n\r\n".encode())
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(), timeout)
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except (ConnectionResetError, OSError):
            pass
    head, _, body = raw.partition(b"\r\n\r\n")
    status = head.split(b"\r\n", 1)[0].split()
    if len(status) < 2 or status[1] != b"200":
        first = head.splitlines()[0] if head else b"(empty response)"
        raise RuntimeError(f"stats fetch failed: {first!r}")
    return body
