"""The Raft consensus core (Copycat ``CopycatServer`` equivalent), CPU oracle.

This is the always-correct reference implementation of the consensus layer the
TPU engine (``copycat_tpu.models``) batches over groups.  Layout:

- ``log``      — entry types, segmented log, Storage levels, clean()/compaction,
  prefix truncation behind snapshots, the fsync policy
- ``state_machine`` — the StateMachine SPI: Commit, executor, log-time timers,
  snapshot_state/restore_state hooks
- ``session``  — server-side sessions: exactly-once, event push queues
- ``snapshot`` — atomic CRC-framed snapshot files (the crash-recovery plane)
- ``raft``     — RaftServer: roles (follower/candidate/leader), RPCs, apply loop
"""

from .log import (
    CommandEntry,
    ConfigurationEntry,
    Entry,
    KeepAliveEntry,
    Log,
    NoOpEntry,
    RegisterEntry,
    Storage,
    StorageLevel,
    UnregisterEntry,
)
from .snapshot import SnapshotStore
from .state_machine import Commit, StateMachine, StateMachineContext, StateMachineExecutor
from .session import ServerSession
from .raft import RaftServer

__all__ = [
    "Entry",
    "RegisterEntry",
    "KeepAliveEntry",
    "UnregisterEntry",
    "CommandEntry",
    "NoOpEntry",
    "ConfigurationEntry",
    "Log",
    "Storage",
    "StorageLevel",
    "Commit",
    "StateMachine",
    "StateMachineContext",
    "StateMachineExecutor",
    "ServerSession",
    "SnapshotStore",
    "RaftServer",
]
