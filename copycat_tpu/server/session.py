"""Server-side sessions: exactly-once command application + event push queues.

The replicated part of a session (id, applied sequences, response cache, event
queue) is computed identically on every server during apply, so a new leader
can resume event delivery after failover.  Only the leader actually *sends*
events (the connection is leader-local, non-replicated state).

Reference behaviors mirrored (SURVEY.md §2.3 "Session protocol"): session id =
registering entry's log index; exactly-once via (session, seq) response
caching; ordered event channel with acks; OPEN/EXPIRED/CLOSED lifecycle that
fans out to state machines (``ResourceManager.java:238-266``).
"""

from __future__ import annotations

import enum
from typing import Any, Callable


class SessionState(enum.Enum):
    OPEN = "open"
    EXPIRED = "expired"
    CLOSED = "closed"


class EventBatch:
    """Events published while applying one entry; one push unit."""

    __slots__ = ("event_index", "prev_event_index", "events")

    def __init__(self, event_index: int, prev_event_index: int, events: list[tuple[str, Any]]):
        self.event_index = event_index
        self.prev_event_index = prev_event_index
        self.events = events


class ServerSession:
    """One client session as seen by a server."""

    def __init__(self, session_id: int, client_id: str, timeout: float) -> None:
        self.id = session_id
        self.client_id = client_id
        self.timeout = timeout
        self.state = SessionState.OPEN

        # --- replicated state (deterministic across servers) ---
        self.command_high = 0  # highest command seq applied
        self.responses: dict[int, tuple[int, Any, str | None]] = {}  # seq -> (index, result, error)
        self.event_index = 0  # last event index assigned
        self.event_ack_index = 0  # highest event index acked by the client
        self.event_queue: list[EventBatch] = []  # unacked batches, ordered
        self.last_keepalive_time = 0.0  # logical clock of last keep-alive entry

        # --- leader-local state (not replicated) ---
        self.connection: Any = None  # client's connection for event push
        self.last_contact = 0.0  # leader wall clock of last request
        self.command_futures: dict[int, Any] = {}  # seq -> future (leader only)
        # Leader-side command sequencing: commands are appended to the log in
        # client seq order; out-of-order arrivals (concurrent submits racing
        # over reconnects) park in pending_ops until the gap fills.
        self.next_append_seq = 0  # 0 = uninitialized on this leader
        self.pending_ops: dict[int, Any] = {}  # seq -> operation awaiting append
        # Multi-group block staging (RaftGroup.command_block): the commit
        # future of the newest append block for this session in this
        # group, so a resent sub-block racing its first attempt can ride
        # the pending commit instead of mis-reading "pruned".
        self.last_block_future: Any = None

        # --- apply-time scratch ---
        self._current_events: list[tuple[str, Any]] = []
        self._event_listener: Callable[[ServerSession], None] | None = None

    # -- event publication (called by state machines during apply) ---------

    def publish(self, event: str, message: Any = None) -> None:
        if self.state is not SessionState.OPEN:
            return
        self._current_events.append((event, message))

    def commit_events(self) -> EventBatch | None:
        """Seal events published during the current apply into a batch."""
        if not self._current_events:
            return None
        prev = self.event_index
        self.event_index = prev + 1
        batch = EventBatch(self.event_index, prev, self._current_events)
        self._current_events = []
        self.event_queue.append(batch)
        return batch

    def ack_events(self, event_index: int) -> None:
        if event_index > self.event_ack_index:
            self.event_ack_index = event_index
            self.event_queue = [b for b in self.event_queue if b.event_index > event_index]

    # -- exactly-once bookkeeping -----------------------------------------

    def cache_response(self, seq: int, index: int, result: Any, error: str | None) -> None:
        self.command_high = max(self.command_high, seq)
        self.responses[seq] = (index, result, error)

    def cached_response(self, seq: int) -> tuple[int, Any, str | None] | None:
        return self.responses.get(seq)

    def ack_commands(self, command_seq: int) -> None:
        """Client confirmed receipt of responses up to command_seq; prune."""
        for seq in [s for s in self.responses if s <= command_seq]:
            del self.responses[seq]

    # -- snapshot round-trip (crash-recovery plane) ------------------------

    def snapshot_dict(self) -> dict:
        """The REPLICATED half of this session as a serializer-writable
        dict (leader-local state — connection, futures, pending ops — is
        deliberately absent: it is rebuilt by live traffic, the same
        contract as leader failover)."""
        return {
            "id": self.id,
            "client_id": self.client_id,
            "timeout": self.timeout,
            "state": self.state.value,
            "command_high": self.command_high,
            "responses": {seq: list(r) for seq, r in self.responses.items()},
            "event_index": self.event_index,
            "event_ack_index": self.event_ack_index,
            "event_queue": [
                (b.event_index, b.prev_event_index, list(b.events))
                for b in self.event_queue],
            "last_keepalive_time": self.last_keepalive_time,
        }

    @classmethod
    def from_snapshot(cls, data: dict) -> "ServerSession":
        session = cls(data["id"], data["client_id"], data["timeout"])
        session.state = SessionState(data["state"])
        session.command_high = data["command_high"]
        session.responses = {seq: tuple(r)
                             for seq, r in data["responses"].items()}
        session.event_index = data["event_index"]
        session.event_ack_index = data["event_ack_index"]
        session.event_queue = [
            EventBatch(ei, prev, [tuple(e) for e in events])
            for ei, prev, events in data["event_queue"]]
        session.last_keepalive_time = data["last_keepalive_time"]
        return session

    # -- lifecycle ---------------------------------------------------------

    @property
    def is_open(self) -> bool:
        return self.state is SessionState.OPEN

    def expire(self) -> None:
        self.state = SessionState.EXPIRED

    def close(self) -> None:
        if self.state is SessionState.OPEN:
            self.state = SessionState.CLOSED

    def __repr__(self) -> str:
        return f"ServerSession(id={self.id}, state={self.state.value})"
