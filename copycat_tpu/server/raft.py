"""RaftServer: the server plane hosting N Raft groups (Copycat
``CopycatServer`` equivalent, multi-raft edition — docs/SHARDING.md).

Everything per-group — term, vote, log, commit/apply cursors, election
timers, replication streams, the session plane, snapshots, the apply
loop — lives in :class:`copycat_tpu.server.raft_group.RaftGroup`; this
class owns what is genuinely SHARED across groups:

- the transport (one listener, one client, one correlated peer
  connection per member — every group's vote/append/install streams and
  the ingress proxy multiplex over it, demultiplexed by the ``group``
  field on the wire);
- the ingress: client sessions connect to ANY member; commands and
  reads are demultiplexed per group by hash routing
  (``StateMachine.route_group``) and staged locally when this member
  leads the owning group, or forwarded to the group's leader as
  :class:`ProxyRequest` sub-blocks (batching stays global, ordering is
  per-group — the compartmentalization shape);
- the stats surface (per-group registries merge under a ``group=``
  label; ``shard.*`` routing counters live on the server registry).

``groups=1`` — the default, also forced by ``COPYCAT_MULTI_GROUP=0`` —
is the single-group plane: one group, no proxying, wire messages carry
``group=None``, and every request is delegated straight to the group's
legacy handlers, bit-identically to the pre-refactor server. The
delegation properties at the bottom keep the classic single-group
surface (``server.term``, ``server.log``, ``server.sessions``...)
pointing at group 0, so single-group embedders and tests see the
original object shape.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Any, Callable

from ..io.serializer import Serializer
from ..io.transport import Address, Connection, Transport, TransportError
from ..protocol import messages as msg
from ..protocol.operations import QueryConsistency
from ..utils import knobs, profiler
from ..utils.health import BlackBox, HealthMonitor
from ..utils.timeseries import SeriesStore
from ..utils.managed import Managed
from ..utils.metrics import MetricsRegistry
from ..utils.tracing import TRACER
from .log import ConfigurationEntry, Storage, StorageLevel
from .raft_group import (  # noqa: F401 - re-exported compat surface
    CANDIDATE,
    FOLLOWER,
    LEADER,
    RaftGroup,
    _EntryCtx,
    _PeerStream,
    dispatch_vector_rows,
)
from .session import SessionState
from .state_machine import StateMachine

__all__ = ["RaftServer", "RaftGroup", "FOLLOWER", "CANDIDATE", "LEADER"]

logger = logging.getLogger(__name__)


class RaftServer(Managed):
    """A Raft replica hosting ``groups`` consensus groups behind one
    transport, one session ingress, and one stats surface."""

    def __init__(
        self,
        address: Address,
        members: list[Address],
        transport: Transport,
        state_machine: StateMachine | Callable[[int], StateMachine],
        storage: Storage | None = None,
        election_timeout: float = 0.5,
        heartbeat_interval: float = 0.1,
        session_timeout: float = 5.0,
        name: str = "raft",
        metrics: MetricsRegistry | None = None,
        groups: int | None = None,
    ) -> None:
        super().__init__()
        self.address = address
        self.boot_members: list[Address] = list(members)
        if address not in self.boot_members:
            self._joining = True
        else:
            self._joining = False
        self.transport = transport
        self.storage = storage or Storage(StorageLevel.MEMORY)
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval
        self.session_timeout = session_timeout
        self.name = name

        # Multi-raft keyspace sharding (docs/SHARDING.md): N groups, one
        # server plane. COPYCAT_GROUPS sets the count when the embedder
        # does not; COPYCAT_MULTI_GROUP=0 forces the single-group plane
        # regardless (the sharding A/B knob).
        if groups is None:
            groups = max(1, knobs.get_int("COPYCAT_GROUPS"))
        if not knobs.get_bool("COPYCAT_MULTI_GROUP"):
            groups = 1
        self.num_groups = groups
        self.single = groups == 1

        # knob-derived shared config (live on the server so tests can
        # flip the pump/lane attributes mid-run; groups read through
        # delegation properties)
        self._repl_pipeline = knobs.get_bool("COPYCAT_REPL_PIPELINE")
        self._repl_window = max(1, knobs.get_int("COPYCAT_REPL_WINDOW"))
        self._repl_depth = max(1, knobs.get_int("COPYCAT_REPL_DEPTH"))
        self._repl_max_inflight = max(self._repl_window, knobs.get_int(
            "COPYCAT_REPL_MAX_INFLIGHT",
            default=self._repl_window * self._repl_depth))
        self._strict_invariants = knobs.get_str(
            "COPYCAT_INVARIANTS", default="") == "strict"
        self._vector_pump = knobs.get_bool("COPYCAT_SERVER_VECTOR_PUMP")
        self._read_pump = knobs.get_bool("COPYCAT_SERVER_READ_PUMP")
        self._parallel_apply = knobs.get_bool("COPYCAT_PARALLEL_APPLY")
        self._apply_fuse = knobs.get_bool("COPYCAT_APPLY_FUSE")
        self._snap_enabled = knobs.get_bool("COPYCAT_SNAPSHOTS")
        self._snap_every = max(1, knobs.get_int("COPYCAT_SNAPSHOT_ENTRIES"))
        self._snap_retain = max(0, knobs.get_int(
            "COPYCAT_SNAPSHOT_RETAIN",
            default=max(64, self._repl_max_inflight)))
        self._snap_chunk = max(4096, knobs.get_int("COPYCAT_SNAP_CHUNK"))
        # Standalone ingress/proxy tier (docs/DEPLOYMENT.md): accept
        # ingress-kind ProxyRequests (and bind proxied sessions for
        # event relay) on any plane; `0` restores the in-server ingress
        # path bit-identically (single-group servers then register no
        # ProxyRequest handler at all).
        self._ingress_tier = knobs.get_bool("COPYCAT_INGRESS_TIER")
        # Edge read tier (docs/EDGE_READS.md): `0` keeps the subscriber
        # registry empty — no seeds, no deltas, the server-read plane
        # bit-identically (the A/B discipline's knob, shared with the
        # client side so one env var flips the whole plane)
        self._edge_enabled = knobs.get_bool("COPYCAT_EDGE_READS")
        self._snap_serializer = Serializer()
        self._fsync_on_commit = (
            self.storage.fsync == "commit"
            and self.storage.level is not StorageLevel.MEMORY)

        self._server = transport.server()
        self._client = transport.client()
        self._peer_connections: dict[Address, Connection] = {}
        self._closing = False

        # Server-level registry: shard.* ingress/routing series (the
        # per-group families live on the group registries and merge into
        # the stats surface under group= labels). On the single-group
        # plane the ONE group shares this registry object, so the
        # pre-refactor names/values are preserved exactly.
        self._metrics = metrics or MetricsRegistry()

        # Health plane (docs/OBSERVABILITY.md "Health & diagnosis"):
        # online anomaly detectors at a fixed cadence + the durable
        # black-box spill, created BEFORE the groups so boot-recovery
        # anomalies (corrupt meta, failed restores) already land in the
        # black-box. COPYCAT_HEALTH=0 removes all of it — no monitor
        # task, no health.* keys, no black-box file, no fsync timing —
        # the pre-health plane bit-identically (A/B).
        self._health_enabled = knobs.get_bool("COPYCAT_HEALTH")
        self._proxy_inflight = 0
        self.blackbox: BlackBox | None = None
        self.health: HealthMonitor | None = None
        # Retrospective telemetry (docs/OBSERVABILITY.md "Retrospective
        # telemetry"): the bounded series ring rides the health
        # monitor's cadence — no task of its own — so it exists exactly
        # when BOTH planes are on. COPYCAT_SERIES=0 removes the ring,
        # the /series routes, the series.*/slo.* keys and the slo_burn
        # detector, restoring the pre-series server bit-identically
        # (A/B). Built BEFORE the monitor: the monitor probes `series`
        # at construction to decide whether slo_burn runs.
        self.series: SeriesStore | None = None
        if self._health_enabled and knobs.get_bool("COPYCAT_SERIES"):
            self.series = SeriesStore(node=self.address, role="member",
                                      metrics=self._metrics)
        # Continuous profiling plane (docs/OBSERVABILITY.md
        # "Profiling"): a refcounted process-wide wall-stack sampler +
        # event-loop hold attribution — acquired BEFORE the monitor
        # (it probes `profiler` at construction to decide whether the
        # loop_stall detector runs) and released in _do_close.
        # COPYCAT_PROFILE=0 makes acquire a no-op returning None: no
        # sampler thread, no profile.* keys, no /profile routes (A/B).
        self.profiler = profiler.acquire(self._metrics,
                                         note_fn=self.health_note)
        if self._health_enabled:
            if self.storage.directory:
                self.blackbox = BlackBox(os.path.join(
                    self.storage.directory,
                    f"{self.name}-{self.address.port}.blackbox"))
                if self.blackbox.recovered:
                    self.blackbox.record(
                        "boot",
                        recovered_events=len(self.blackbox.recovered))
            self.health = HealthMonitor(self)

        def build_machine(g: int) -> StateMachine:
            if callable(state_machine) \
                    and not isinstance(state_machine, StateMachine):
                return state_machine(g)
            if g == 0:
                return state_machine
            # a bare instance with >1 groups: construct siblings from the
            # class — machines needing arguments must come via a factory
            return type(state_machine)()

        # Cross-group apply fusion (docs/SHARDING.md "Apply ordering"):
        # groups stage their device-eligible vector runs here instead of
        # paying one engine round each; the collector dispatches ONCE at
        # the end of the event-loop turn with mixed groups_idx rows —
        # one DeviceEngine.run_vector per server turn no matter how many
        # groups' commits advanced. COPYCAT_APPLY_FUSE=0 keeps the
        # per-group dispatch (the A/B lane). All groups share one engine
        # (docs/SHARDING.md), so mixing rows is free; per-group FIFO
        # holds because runs are staged in per-group log order and the
        # engine's stable group sort preserves row order within a group.
        # Initialized BEFORE the groups: boot recovery inside
        # RaftGroup.__init__ reaches flush_fused via _restore_snapshot.
        self._fused_runs: list[tuple[RaftGroup, list]] = []
        self._fuse_scheduled = False
        self._m_apply_fused = self._metrics.counter("apply.fused_dispatches")
        self._m_apply_fused_rows = self._metrics.histogram(
            "apply.fused_rows")
        self._m_apply_fused_groups = self._metrics.histogram(
            "apply.fused_groups")

        self.groups: list[RaftGroup] = []
        for g in range(groups):
            reg = self._metrics if self.single else MetricsRegistry()
            self.groups.append(RaftGroup(self, g, build_machine(g), reg))
        machine_cls = type(self.groups[0].state_machine)
        self._route_group_fn = getattr(machine_cls, "route_group", None)

        # Ingress-side phase histograms of the causal-tracing plane
        # (docs/OBSERVABILITY.md): fed only by traced requests. On the
        # single-group plane the registry is shared with group 0, so
        # the family sits in one snapshot either way.
        self._m_lat_ingress_queue = self._metrics.histogram(
            "latency.ingress_queue_ms")
        self._m_lat_proxy_hop = self._metrics.histogram(
            "latency.proxy_hop_ms")
        if not self.single:
            m = self._metrics
            self._m_shard_local = m.counter("shard.commands_local")
            self._m_shard_proxied = m.counter("shard.commands_proxied")
            self._m_shard_reads_local = m.counter("shard.reads_local")
            self._m_shard_reads_proxied = m.counter("shard.reads_proxied")
            self._m_shard_registers = m.counter("shard.register_fanouts")
            self._m_routed = {
                g: m.counter("shard.routed", group=str(g))
                for g in range(groups)}
        # per-(session, group) in-order dispatch chains: sub-blocks of
        # one session bound for one group leader are delivered strictly
        # in submission order, so the group can append in arrival order
        # (the gapped-staging contract of RaftGroup.command_block)
        self._chains: dict[tuple, asyncio.Future] = {}
        # last known client connection per session (multi-group): group
        # replicas late-bind it when their RegisterEntry applies — the
        # ingress's follower apply can land AFTER the client's first
        # requests touched the (then-nonexistent) replica
        self._session_conns: dict[int, Connection] = {}

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def _do_open(self) -> None:
        self._closing = False
        await self._server.listen(self.address, self._accept)
        if self._joining:
            await self._join_cluster()
        for grp in self.groups:
            grp.start()
        if self.health is not None:
            self.health.start()
        logger.info("%s listening at %s (members=%s, groups=%d)", self.name,
                    self.address, self.groups[0].members, self.num_groups)

    async def _do_close(self) -> None:
        self._closing = True
        try:
            # staged-but-undispatched fused rows complete (and ack)
            # before the groups fail whatever else is pending
            self.flush_fused()
        except Exception:  # noqa: BLE001 — close must proceed
            logger.exception("fused apply flush at close failed")
        if self.health is not None:
            self.health.stop()
        for grp in self.groups:
            grp.shutdown()
        await self._server.close()
        await self._client.close()
        self._peer_connections.clear()
        if self.blackbox is not None:
            self.blackbox.close()
        # last release per process stops the sampler + unpatches the
        # loop; _cancel_timers (the SIGKILL-shaped stop) deliberately
        # does NOT release — a crash doesn't run destructors either
        profiler.release(self.profiler, self._metrics)
        self.profiler = None

    def _cancel_timers(self) -> None:
        # crash_server (testing/nemesis.py) calls this for its
        # SIGKILL-shaped stop: the health pump dies with the process too
        # (the black-box file handle is deliberately NOT closed — a
        # crash leaves whatever the last flush wrote, nothing more)
        if self.health is not None:
            self.health.stop()
        for grp in self.groups:
            grp._cancel_timers()

    def _stop_replication(self) -> None:
        for grp in self.groups:
            grp._stop_replication()

    async def leave(self) -> None:
        """Gracefully leave the cluster (reference server leave test
        path). Membership rides the metadata group's log; the applied
        configuration propagates to every group."""
        g0 = self.groups[0]
        if g0.role == LEADER:
            await g0._append_and_wait(ConfigurationEntry(
                members=[m for m in g0.members if m != self.address]))
        else:
            conn = await self._leader_connection()
            if conn is not None:
                response = await conn.send(
                    msg.LeaveRequest(member=self.address))
                response.raise_if_error()

    # ------------------------------------------------------------------
    # connections
    # ------------------------------------------------------------------

    def _accept(self, connection: Connection) -> None:
        # raft RPCs route through the server-level shims below (attribute
        # lookup at call time: nemesis/tests may patch them per instance),
        # which demultiplex on the wire ``group`` field
        connection.handler(msg.VoteRequest, lambda m: self._on_vote(m))
        connection.handler(msg.AppendRequest, lambda m: self._on_append(m))
        connection.handler(msg.InstallRequest,
                           lambda m: self._on_install(m))
        if self.single:
            g0 = self.groups[0]
            connection.handler(
                msg.RegisterRequest,
                lambda m: g0._on_register(connection, m))
            connection.handler(
                msg.KeepAliveRequest,
                lambda m: g0._on_keepalive(connection, m))
            connection.handler(msg.UnregisterRequest, g0._on_unregister)
            connection.handler(
                msg.CommandRequest,
                lambda m: g0._on_command(connection, m))
            connection.handler(
                msg.CommandBatchRequest,
                lambda m: g0._on_command_batch(connection, m))
            connection.handler(msg.QueryRequest, g0._on_query)
            connection.handler(msg.QueryBatchRequest, g0._on_query_batch)
            if self._ingress_tier:
                # standalone ingress proxies (docs/DEPLOYMENT.md) speak
                # ProxyRequest to single-group clusters too; with
                # COPYCAT_INGRESS_TIER=0 the handler is not registered
                # and the pre-deployment wire surface is bit-identical
                connection.handler(
                    msg.ProxyRequest,
                    lambda m: self._on_proxy(connection, m))
        else:
            connection.handler(
                msg.RegisterRequest,
                lambda m: self._ms_register(connection, m))
            connection.handler(
                msg.KeepAliveRequest,
                lambda m: self._ms_keepalive(connection, m))
            connection.handler(msg.UnregisterRequest, self._ms_unregister)
            connection.handler(
                msg.CommandRequest,
                lambda m: self._ms_command(connection, m))
            connection.handler(
                msg.CommandBatchRequest,
                lambda m: self._ms_command_batch(connection, m))
            connection.handler(msg.QueryRequest, self._ms_query)
            connection.handler(msg.QueryBatchRequest, self._ms_query_batch)
            connection.handler(msg.ProxyRequest,
                               lambda m: self._on_proxy(connection, m))
        connection.handler(msg.JoinRequest, self._on_join)
        connection.handler(msg.LeaveRequest, self._on_leave)

    def _group_of(self, request: Any) -> RaftGroup:
        g = getattr(request, "group", None) or 0
        if not 0 <= g < self.num_groups:
            # a mixed-config cluster (different COPYCAT_GROUPS /
            # COPYCAT_MULTI_GROUP per member) must surface loudly at the
            # sender, not as an anonymous IndexError in the handler
            raise msg.ProtocolError(
                msg.INTERNAL,
                f"group {g} not hosted here (this member runs "
                f"{self.num_groups} group(s)) — the group count must be "
                f"uniform across the cluster (docs/SHARDING.md)")
        return self.groups[g]

    async def _on_vote(self, request: msg.VoteRequest) -> msg.VoteResponse:
        return await self._group_of(request)._on_vote(request)

    async def _on_append(self, request: msg.AppendRequest
                         ) -> msg.AppendResponse:
        return await self._group_of(request)._on_append(request)

    async def _on_install(self, request: msg.InstallRequest
                          ) -> msg.InstallResponse:
        return await self._group_of(request)._on_install(request)

    async def _peer_connection(self, peer: Address) -> Connection | None:
        conn = self._peer_connections.get(peer)
        if conn is not None and not conn.closed:
            return conn
        try:
            conn = await self._client.connect(peer)
        except (TransportError, OSError):
            return None
        self._peer_connections[peer] = conn
        return conn

    async def _leader_connection(self) -> Connection | None:
        leader = self.groups[0].leader_address
        if leader is None or leader == self.address:
            return None
        return await self._peer_connection(leader)

    # ------------------------------------------------------------------
    # membership (rides the metadata group's log)
    # ------------------------------------------------------------------

    async def _join_cluster(self) -> None:
        for attempt in range(20):
            for member in self.boot_members:
                if member == self.address:
                    continue
                conn = None
                try:
                    conn = await self._client.connect(member)
                    response = await asyncio.wait_for(
                        conn.send(msg.JoinRequest(member=self.address)), 2.0)
                except (TransportError, OSError, asyncio.TimeoutError):
                    continue
                if response.ok:
                    self._adopt_members(list(response.members))
                    self._joining = False
                    return
                if response.error == msg.NOT_LEADER and response.leader:
                    try:
                        conn2 = await self._client.connect(response.leader)
                        response = await asyncio.wait_for(
                            conn2.send(msg.JoinRequest(member=self.address)),
                            2.0)
                        if response.ok:
                            self._adopt_members(list(response.members))
                            self._joining = False
                            return
                    except (TransportError, OSError, asyncio.TimeoutError):
                        continue
            await asyncio.sleep(0.2)
        raise msg.ProtocolError(msg.NO_LEADER, "unable to join cluster")

    def _adopt_members(self, members: list[Address]) -> None:
        for grp in self.groups:
            grp.members = list(members)

    def _membership_applied(self, members: list[Address]) -> None:
        """Group 0 applied a ConfigurationEntry: propagate the view to
        groups 1..G-1 (multi-group only; see RaftGroup._apply_configuration
        for the single-group/metadata-group behavior)."""
        for grp in self.groups[1:]:
            grp._adopt_members(members)

    async def _on_join(self, request: msg.JoinRequest) -> msg.JoinResponse:
        g0 = self.groups[0]
        if g0.role != LEADER:
            return msg.JoinResponse(error=msg.NOT_LEADER,
                                    leader=g0.leader_address)
        member = request.member
        if member not in g0.members:
            new_members = g0.members + [member]
            await g0._append_and_wait(
                ConfigurationEntry(members=new_members))
        return msg.JoinResponse(members=g0.members)

    async def _on_leave(self, request: msg.LeaveRequest) -> msg.LeaveResponse:
        g0 = self.groups[0]
        if g0.role != LEADER:
            return msg.LeaveResponse(error=msg.NOT_LEADER,
                                     leader=g0.leader_address)
        member = request.member
        if member in g0.members:
            new_members = [m for m in g0.members if m != member]
            await g0._append_and_wait(
                ConfigurationEntry(members=new_members))
        return msg.LeaveResponse(members=g0.members)

    # ------------------------------------------------------------------
    # multi-group ingress: routing, proxying, aggregation
    # (docs/SHARDING.md — only wired when ``groups > 1``)
    # ------------------------------------------------------------------

    def _route(self, operation: Any) -> int:
        """The owning group for one operation: the state machine class's
        ``route_group`` (hash routing over resource keys / instance ids
        for the ResourceManager), deterministic across members and
        restarts; operations without affinity land on group 0."""
        fn = self._route_group_fn
        if fn is None:
            return 0
        g = fn(operation, self.num_groups)
        return g if 0 <= g < self.num_groups else 0

    def _client_index(self, index: Any, g: int) -> int:
        """Extract the client's per-group read high-water from a request
        ``index`` field: multi-group clients send ``{group: index}``."""
        if isinstance(index, dict):
            return index.get(g, 0) or 0
        if g == 0 and isinstance(index, int):
            return index
        return 0

    def _tag_index(self, index: int, g: int) -> int:
        """Stamp a per-group log index with its group so the client can
        keep per-group read cursors: ``index * G + g`` (group 0 keeps
        untagged-compatible residue 0)."""
        return index * self.num_groups + g if index else index

    def _touch_session(self, session_id: int, connection: Connection,
                       now: float) -> None:
        """Attach the client's connection + contact time to every LOCAL
        group replica of the session. The ingress (this member) pushes
        each group's events from its own apply of that group's log —
        replicas that have not applied their RegisterEntry yet are
        attached on the next touch (events meanwhile queue in the
        replicated event queue and flush on the next keep-alive)."""
        g0 = self.groups[0]
        if (session_id in g0.sessions
                or g0.last_applied * self.num_groups < session_id):
            # record for late-binding replicas ONLY while the session is
            # live here or its register has not applied locally yet — a
            # straggler request after the unregister applied would
            # otherwise re-insert and pin its Connection forever (the
            # group-0 unregister apply is the map's removal path)
            self._session_conns[session_id] = connection
        for grp in self.groups:
            session = grp.sessions.get(session_id)
            if session is not None:
                attached = session.connection is not connection
                session.connection = connection
                session.last_contact = now
                if attached and session.event_queue:
                    # events sealed while no (or a dead) connection was
                    # bound: deliver now instead of at the next keep-alive
                    grp._flush_events(session)

    async def _chained(self, key: tuple, thunk: Callable) -> Any:
        """Launch-order gate per (session, group): consecutive
        sub-blocks of one session bound for one group are handed to the
        transport (or the local group's synchronous staging prefix) in
        submission order — which both transports and the handler
        dispatch preserve end to end — WITHOUT serializing the round
        trips, so a session can keep a full pipeline of blocks in
        flight (the ingress stays windowed, not stop-and-wait). During
        a failover window the proxy's retry loop can still reorder
        relative to a later wave; the group's dedup then fails those
        ops LOUDLY (seq-below-cursor errors), never silently
        (docs/SHARDING.md "failover windows")."""
        from ..utils.tasks import spawn

        loop = asyncio.get_running_loop()
        prev = self._chains.get(key)
        gate: asyncio.Future = loop.create_future()
        self._chains[key] = gate
        try:
            if prev is not None:
                await asyncio.shield(prev)
            task = spawn(thunk(), name="dispatch-commands")
        finally:
            # launched (or failed to): the NEXT sub-block may launch;
            # FIFO task scheduling runs the synchronous send/stage
            # prefixes in creation order
            if not gate.done():
                gate.set_result(None)
            if self._chains.get(key) is gate:
                del self._chains[key]
        return await task

    def _trace_span(self, trace: int, name: str, t0: float, t1: float,
                    hist=None, **meta: Any) -> None:
        """Ingress-side causal span (utils/tracing.py vocabulary),
        tagged with this member so the cross-member assembly can place
        the ingress phases, plus the matching ``latency.*`` histogram."""
        TRACER.span(trace, name, t0, t1, member=str(self.address), **meta)
        if hist is not None:
            hist.record((t1 - t0) * 1e3)

    async def _proxy(self, g: int, kind: str, payload: Any,
                     trace: int | None = None) -> msg.ProxyResponse:
        """Dispatch one staged sub-request to group ``g``'s leader —
        locally when this member leads the group, else as a ProxyRequest
        over the peer connection, retrying toward the group's current
        leader view (which updates via the group's own append stream).
        ``trace`` (the originating trace id) rides the ProxyRequest's
        optional trailing field; each wire attempt records a
        ``proxy.hop`` span (failed attempts tagged ``error=``)."""
        # in-flight accounting feeds the health plane's ingress-backlog
        # detector: sub-requests parked in the retry loop (a saturated
        # or unreachable group leader) are exactly the backlog
        self._proxy_inflight += 1
        try:
            return await self._proxy_dispatch(g, kind, payload, trace)
        finally:
            self._proxy_inflight -= 1

    async def _proxy_dispatch(self, g: int, kind: str, payload: Any,
                              trace: int | None = None
                              ) -> msg.ProxyResponse:
        grp = self.groups[g]
        backoff = 0.01
        # the per-try budget must cover COMMIT latency, not just the
        # wire: under window saturation a staged sub-block legitimately
        # waits out the whole replication queue before its outcome
        # exists, and a timeout here CANCELS the in-flight send —
        # re-sending a block whose first copy already appended (found by
        # the sharded bench at full depth: retry storms surfacing as
        # seq-below-cursor errors). Routing refusals (NOT_LEADER) come
        # back fast regardless, so retry responsiveness keeps.
        try_budget = max(self.session_timeout, self.election_timeout * 4)
        deadline = time.monotonic() + max(self.session_timeout,
                                          self.election_timeout * 8)
        while True:
            if self._closing:
                return msg.ProxyResponse(error=msg.NO_LEADER,
                                         error_detail="server closing")
            if grp.role == LEADER:
                return await self._proxy_local(grp, kind, payload, trace)
            leader = grp.leader_address
            response = None
            if leader is not None and leader != self.address:
                conn = await self._peer_connection(leader)
                if conn is not None:
                    t_hop = (time.perf_counter() if trace is not None
                             else 0.0)
                    try:
                        response = await asyncio.wait_for(
                            conn.send(msg.ProxyRequest(
                                group=g, kind=kind, payload=payload,
                                trace=trace)),
                            try_budget)
                    except (TransportError, OSError, asyncio.TimeoutError):
                        response = None
                    if trace is not None:
                        if response is not None:
                            self._trace_span(trace, "proxy.hop", t_hop,
                                             time.perf_counter(),
                                             self._m_lat_proxy_hop,
                                             group=g, to=str(leader))
                        else:
                            # the failed attempt stays on the timeline:
                            # an assembly missing the group-side spans
                            # shows WHERE the request died
                            self._trace_span(trace, "proxy.hop", t_hop,
                                             time.perf_counter(),
                                             self._m_lat_proxy_hop,
                                             group=g, to=str(leader),
                                             error="unreachable")
            if response is not None and response.error not in (
                    msg.NOT_LEADER, msg.NO_LEADER):
                return response
            if time.monotonic() > deadline:
                return (response if response is not None
                        else msg.ProxyResponse(
                            error=msg.NO_LEADER,
                            error_detail=f"group {g} has no reachable "
                                         f"leader"))
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, 0.1)

    async def _on_proxy(self, connection: Connection,
                        request: msg.ProxyRequest) -> msg.ProxyResponse:
        trace = request.trace
        kind = request.kind
        grp = self._group_of(request)
        from_ingress = kind.startswith("ingress:")
        if from_ingress:
            # a standalone ingress proxy (docs/DEPLOYMENT.md): same
            # staging entry points, PLUS this member binds the proxied
            # session to the ingress's connection so event pushes flow
            # member -> ingress -> client. The prefix is data, not
            # schema — the wire frames are unchanged.
            if not self._ingress_tier:
                return msg.ProxyResponse(
                    error=msg.INTERNAL,
                    error_detail="ingress tier disabled on this member "
                                 "(COPYCAT_INGRESS_TIER=0)")
            kind = kind[len("ingress:"):]
        response = await self._proxy_local(grp, kind, request.payload,
                                           trace)
        if from_ingress and not response.error:
            self._bind_ingress_session(grp, kind, request.payload,
                                       response, connection)
        if trace is not None:
            response.trace = trace  # echo: the hop stays correlated
        return response

    def _bind_ingress_session(self, grp: RaftGroup, kind: str,
                              payload: Any, response: msg.ProxyResponse,
                              connection: Connection) -> None:
        """Attach an ingress-proxied session to the ingress's peer
        connection on THIS group's replica (the ingress holds the real
        client connection and relays pushes). The binding follows the
        proxy stream: after a leader change the next proxied
        keep-alive/command lands here and re-binds on the new leader —
        events meanwhile queue in the replicated event queue, exactly
        the reconnect contract direct clients get."""
        if kind == "register":
            sid = response.result
        elif kind in ("keepalive", "commands"):
            sid = payload[0]
        elif kind == "unregister":
            return  # the unregister apply removed the session
        else:
            return
        session = grp.sessions.get(sid)
        if session is None:
            return
        attached = session.connection is not connection
        session.connection = connection
        session.last_contact = time.monotonic()
        if (attached or kind == "keepalive") and session.event_queue:
            grp._flush_events(session)

    async def _proxy_local(self, grp: RaftGroup, kind: str, payload: Any,
                           trace: int | None = None) -> msg.ProxyResponse:
        """Serve one staged sub-request on a group this member leads
        (the proxy handler on the receiving leader, and the local
        shortcut at the ingress)."""
        try:
            if kind == "commands":
                session_id, entries = payload
                out, err = await grp.command_block(session_id,
                                                   [tuple(e)
                                                    for e in entries],
                                                   trace)
                if err is not None:
                    code, detail, leader = err
                    return msg.ProxyResponse(error=code, error_detail=detail,
                                             leader=leader)
                return msg.ProxyResponse(result=out)
            if kind == "register":
                client_id, timeout, session_id = payload
                if grp.role != LEADER:
                    return grp._not_leader(msg.ProxyResponse)
                _, sid, _ = await grp.register_local(client_id, timeout,
                                                     session_id)
                return msg.ProxyResponse(result=sid)
            if kind == "keepalive":
                session_id, command_seq, event_index = payload
                if grp.role != LEADER:
                    return grp._not_leader(msg.ProxyResponse)
                session = grp.sessions.get(session_id)
                if session is None \
                        or session.state is not SessionState.OPEN:
                    return msg.ProxyResponse(error=msg.UNKNOWN_SESSION)
                await grp.keepalive_local(session_id, command_seq,
                                          event_index)
                return msg.ProxyResponse(result=True)
            if kind == "unregister":
                session_id = payload
                if grp.role != LEADER:
                    return grp._not_leader(msg.ProxyResponse)
                if session_id in grp.sessions:
                    await grp.unregister_local(session_id)
                return msg.ProxyResponse(result=True)
            if kind == "query":
                session_id, client_index, consistency, operations = payload
                index, entries, err = await grp.serve_query(
                    session_id, client_index,
                    QueryConsistency(consistency), list(operations))
                if err is not None:
                    code, detail, leader = err
                    return msg.ProxyResponse(error=code, error_detail=detail,
                                             leader=leader)
                return msg.ProxyResponse(result=(index, entries))
        except msg.ProtocolError as e:
            return msg.ProxyResponse(error=e.code, error_detail=e.detail,
                                     leader=e.leader)
        return msg.ProxyResponse(error=msg.INTERNAL,
                                 error_detail=f"unknown proxy kind {kind!r}")

    # -- session ingress (multi-group handlers) ------------------------

    async def _ms_register(self, connection: Connection,
                           request: msg.RegisterRequest
                           ) -> msg.RegisterResponse:
        timeout = request.timeout or self.session_timeout
        self._m_shard_registers.inc()
        response = await self._proxy(
            0, "register", (request.client_id, timeout, None))
        if response.error:
            return msg.RegisterResponse(error=response.error,
                                        leader=None,
                                        members=self.groups[0].members)
        sid = response.result
        outs = await asyncio.gather(*(
            self._proxy(g, "register", (request.client_id, timeout, sid))
            for g in range(1, self.num_groups)))
        for out in outs:
            if out.error:
                # a keyspace slice has no session: fail the register;
                # the client retries (the orphaned replicas expire by
                # timeout, never having served a command)
                return msg.RegisterResponse(
                    error=out.error, error_detail=out.error_detail,
                    members=self.groups[0].members)
        self._touch_session(sid, connection, time.monotonic())
        return msg.RegisterResponse(session_id=sid, timeout=timeout,
                                    members=self.groups[0].members,
                                    groups=self.num_groups)

    async def _ms_keepalive(self, connection: Connection,
                            request: msg.KeepAliveRequest
                            ) -> msg.KeepAliveResponse:
        sid = request.session_id
        members = self.groups[0].members
        # no local liveness precheck: this member's follower replicas may
        # lag the register apply — each group's LEADER is authoritative
        # (the group-0 proxy outcome decides UNKNOWN_SESSION below)
        self._touch_session(sid, connection, time.monotonic())
        if getattr(request, "unsubscribe", None):
            # member-local edge bookkeeping (docs/EDGE_READS.md): evicted
            # instances retire from whichever group's registry holds them
            for grp in self.groups:
                grp.edge_unsubscribe(sid, request.unsubscribe)
        ev = request.event_index
        seq = request.command_seq or 0

        def ev_for(g: int) -> int:
            if isinstance(ev, dict):
                return ev.get(g, 0) or 0
            return (ev or 0) if g == 0 else 0

        outs = await asyncio.gather(*(
            self._proxy(g, "keepalive", (sid, seq, ev_for(g)))
            for g in range(self.num_groups)))
        if outs[0].error:
            return msg.KeepAliveResponse(error=outs[0].error,
                                         members=members)
        # resend whatever each local replica still holds unacked (the
        # ingress owns every group's event channel for this session)
        for grp in self.groups:
            session = grp.sessions.get(sid)
            if session is not None:
                grp._flush_events(session)
        return msg.KeepAliveResponse(members=members)

    async def _ms_unregister(self, request: msg.UnregisterRequest
                             ) -> msg.UnregisterResponse:
        outs = await asyncio.gather(*(
            self._proxy(g, "unregister", request.session_id)
            for g in range(self.num_groups)))
        first = outs[0]
        if first.error and first.error != msg.UNKNOWN_SESSION:
            return msg.UnregisterResponse(error=first.error,
                                          leader=first.leader)
        return msg.UnregisterResponse()

    async def _dispatch_commands(self, g: int, session_id: int, sub: list,
                                 trace: int | None = None,
                                 t0: float = 0.0) -> Any:
        """One group's command sub-block, in per-(session, group) order;
        returns the tagged per-entry outcomes, or ``(code, detail,
        leader)`` for a response-level failure. When traced, the wait
        from ingress receipt (``t0``) until the dispatch chain released
        this sub-block records as ``ingress.queue``."""
        grp = self.groups[g]
        if grp.role == LEADER:
            self._m_shard_local.inc(len(sub))
        else:
            self._m_shard_proxied.inc(len(sub))
        self._m_routed[g].inc(len(sub))

        async def dispatch() -> msg.ProxyResponse:
            if trace is not None:
                self._trace_span(trace, "ingress.queue", t0,
                                 time.perf_counter(),
                                 self._m_lat_ingress_queue, group=g,
                                 n=len(sub))
            return await self._proxy(g, "commands", (session_id, sub),
                                     trace)

        response = await self._chained((session_id, g), dispatch)
        if response.error:
            return (response.error, response.error_detail or "",
                    response.leader)
        out = response.result or []
        return [(seq, self._tag_index(idx, g), res, code, det)
                for seq, idx, res, code, det in (tuple(e) for e in out)]

    async def _ms_command_batch(self, connection: Connection,
                                request: msg.CommandBatchRequest
                                ) -> msg.CommandBatchResponse:
        sid = request.session_id
        # group leaders are authoritative for session liveness (this
        # member's replicas may lag the register apply); rep0 only feeds
        # the response's event_index when already present
        rep0 = self.groups[0].sessions.get(sid)
        self._touch_session(sid, connection, time.monotonic())
        entries = request.entries or []
        trace = request.trace
        t0 = time.perf_counter() if trace is not None else 0.0
        buckets: dict[int, list] = {}
        for seq, op in entries:
            buckets.setdefault(self._route(op), []).append((seq, op))
        results = await asyncio.gather(*(
            self._dispatch_commands(g, sid, sub, trace, t0)
            for g, sub in buckets.items()))
        merged: dict[int, tuple] = {}
        for res in results:
            if isinstance(res, tuple):  # response-level (code, detail, ...)
                code, detail, leader = res
                return msg.CommandBatchResponse(
                    error=code, error_detail=detail, leader=leader)
            for entry in res:
                merged[entry[0]] = entry
        out = [merged.get(seq, (seq, 0, None, msg.INTERNAL,
                                "sub-block outcome missing"))
               for seq, _ in entries]
        return msg.CommandBatchResponse(
            event_index=rep0.event_index if rep0 is not None else 0,
            entries=out)

    async def _ms_command(self, connection: Connection,
                          request: msg.CommandRequest
                          ) -> msg.CommandResponse:
        sid = request.session_id
        rep0 = self.groups[0].sessions.get(sid)
        self._touch_session(sid, connection, time.monotonic())
        g = self._route(request.operation)
        trace = request.trace
        res = await self._dispatch_commands(
            g, sid, [(request.seq, request.operation)], trace,
            time.perf_counter() if trace is not None else 0.0)
        if isinstance(res, tuple):
            code, detail, leader = res
            return msg.CommandResponse(error=code, error_detail=detail,
                                       leader=leader)
        event_index = rep0.event_index if rep0 is not None else 0
        _, index, result, code, detail = res[0]
        if code:
            return msg.CommandResponse(error=code, error_detail=detail,
                                       index=index,
                                       event_index=event_index)
        return msg.CommandResponse(index=index, result=result,
                                   event_index=event_index)

    async def _serve_reads(self, g: int, session_id: int, index: Any,
                           consistency: QueryConsistency, operations: list
                           ) -> tuple[int, list | None, tuple | None]:
        """Route one group's read bucket: leaders (and, for
        sequential/causal levels, any member — this one) serve locally;
        linearizable levels on remotely-led groups proxy to the leader so
        the reads join ITS read window and share its confirm round."""
        grp = self.groups[g]
        ci = self._client_index(index, g)
        leader_required = consistency in (
            QueryConsistency.LINEARIZABLE,
            QueryConsistency.BOUNDED_LINEARIZABLE)
        if leader_required and grp.role != LEADER:
            self._m_shard_reads_proxied.inc(len(operations))
            response = await self._proxy(
                g, "query",
                (session_id, ci, consistency.value, operations))
            if response.error:
                return 0, None, (response.error,
                                 response.error_detail or "",
                                 response.leader)
            served_index, entries = response.result
            return served_index, entries, None
        self._m_shard_reads_local.inc(len(operations))
        return await grp.serve_query(session_id, ci, consistency,
                                     operations)

    def _ms_edge_seed(self, request: Any, g: int,
                      operations: list, served_index: int) -> list | None:
        """Multi-group edge registration (docs/EDGE_READS.md): the
        ingress (this member) holds the session's connection AND
        applies every group's log, so it both registers and pushes.
        Seeds ride group-LOCAL versions — instance ids are self-routing
        (``iid % groups``), so the client recovers the group."""
        if not getattr(request, "subscribe", None):
            return None
        consistency = QueryConsistency(request.consistency or "linearizable")
        if consistency in (QueryConsistency.LINEARIZABLE,
                           QueryConsistency.BOUNDED_LINEARIZABLE):
            return None  # linearizable levels never serve from the edge
        return self.groups[g].edge_register(
            request.session_id, operations, served_index)

    async def _ms_query(self, request: msg.QueryRequest
                        ) -> msg.QueryResponse:
        consistency = QueryConsistency(request.consistency or "linearizable")
        g = self._route(request.operation)
        served_index, entries, err = await self._serve_reads(
            g, request.session_id, request.index, consistency,
            [request.operation])
        if err is not None:
            code, detail, _leader = err
            if code in (msg.NOT_LEADER, msg.NO_LEADER):
                # the single-group shape: the client treats this as
                # "re-route"; any member can ingress, so no leader pin
                return msg.QueryResponse(error=code)
            return msg.QueryResponse(error=code, error_detail=detail)
        result, code, detail = entries[0]
        tagged = self._tag_index(served_index, g)
        if code:
            return msg.QueryResponse(error=code, error_detail=detail,
                                     index=tagged)
        response = msg.QueryResponse(index=tagged, result=result)
        seeds = self._ms_edge_seed(request, g, [request.operation],
                                   served_index)
        if seeds:
            response.edge = seeds
        return response

    async def _ms_query_batch(self, request: msg.QueryBatchRequest
                              ) -> msg.QueryBatchResponse:
        consistency = QueryConsistency(request.consistency or "linearizable")
        operations = request.operations or []
        buckets: dict[int, list] = {}  # g -> [(pos, op)]
        for pos, op in enumerate(operations):
            buckets.setdefault(self._route(op), []).append((pos, op))
        outs = await asyncio.gather(*(
            self._serve_reads(g, request.session_id, request.index,
                              consistency, [op for _, op in sub])
            for g, sub in buckets.items()))
        entries: list = [None] * len(operations)
        index: dict[int, int] = {}
        edge: list = []
        for (g, sub), (served_index, served, err) in zip(buckets.items(),
                                                         outs):
            if err is not None:
                code, detail, _leader = err
                if code in (msg.NOT_LEADER, msg.NO_LEADER):
                    return msg.QueryBatchResponse(error=code)
                return msg.QueryBatchResponse(error=code,
                                              error_detail=detail)
            if served_index:
                index[g] = served_index
            for (pos, _op), entry in zip(sub, served):
                entries[pos] = tuple(entry)
            seeds = self._ms_edge_seed(request, g, [op for _, op in sub],
                                       served_index)
            if seeds:
                edge.extend(seeds)
        response = msg.QueryBatchResponse(index=index, entries=entries)
        if edge:
            response.edge = edge
        return response

    # ------------------------------------------------------------------
    # cross-group apply fusion (docs/SHARDING.md "Apply ordering")
    # ------------------------------------------------------------------

    def stage_vector_run(self, grp: RaftGroup, run: list) -> None:
        """Stage one group's vector run for the turn's fused dispatch.

        The dispatch runs at the end of the current event-loop turn
        (``call_soon``), so every group whose commit advanced this turn
        contributes rows to ONE engine round; a group that hits a
        dependency conflict before then forces :meth:`flush_fused`
        inline (the staged effects must land before the conflicting
        entry applies)."""
        self._fused_runs.append((grp, run))
        if self._fuse_scheduled:
            return
        self._fuse_scheduled = True
        try:
            asyncio.get_running_loop().call_soon(self._fused_tick)
        except RuntimeError:
            # no running loop (synchronous replay harness): dispatch now
            self.flush_fused()

    def _fused_tick(self) -> None:
        try:
            self.flush_fused()
        except Exception:  # noqa: BLE001 — a loop callback must not raise
            logger.exception("fused apply dispatch failed")

    def flush_fused(self) -> None:
        """Dispatch every staged run as ONE mixed-rows engine round,
        then finalize per group in staging (= per-group log) order.
        Forced synchronously by dependency conflicts, gated reads,
        snapshot captures and server close; otherwise runs once per
        event-loop turn. An empty collector is a free no-op (every
        forced-flush site relies on that).

        The documented architecture shares ONE engine across groups
        (``_manager_factory``), so the partition below is normally a
        single round; an embedder wiring per-group engines still gets
        correct (per-engine) dispatches instead of corrupted mixed
        ``groups_idx`` rows."""
        self._fuse_scheduled = False
        staged, self._fused_runs = self._fused_runs, []
        if not staged:
            return
        engines: list = []   # insertion-ordered; runs stay in log order
        per_engine: dict[int, list] = {}
        for grp, run in staged:
            engine = grp.state_machine.device_engine
            bucket = per_engine.get(id(engine))
            if bucket is None:
                bucket = per_engine[id(engine)] = []
                engines.append(engine)
            bucket.append((grp, run))
        for engine in engines:
            self._flush_fused_engine(engine, per_engine[id(engine)])

    def _flush_fused_engine(self, engine, staged: list) -> None:
        rows = [row for _, run in staged for row in run]
        self._m_apply_fused.inc()
        self._m_apply_fused_rows.record(len(rows))
        self._m_apply_fused_groups.record(
            len({g.group_id for g, _ in staged}))
        # mid-batch forced flushes drain the window's in-flight
        # generator chains from EARLIER entries inside the shared
        # dispatch helper, so each group's device-op order follows its
        # log
        raws, pump_error = dispatch_vector_rows(engine, engine.window,
                                                rows)
        offset = 0
        for grp, run in staged:
            grp._finalize_vector_run(
                run,
                raws[offset:offset + len(run)] if pump_error is None
                else [], pump_error)
            offset += len(run)

    def drop_fused(self, grp: RaftGroup) -> None:
        """Discard ``grp``'s staged rows (group shutdown: its commit
        futures are failing with NO_LEADER and a restart replays the
        uncleaned entries from the log)."""
        if self._fused_runs:
            self._fused_runs = [(g, r) for g, r in self._fused_runs
                                if g is not grp]
        grp._stage_keys.clear()
        grp._stage_sessions.clear()
        grp._stage_rows = 0

    # ------------------------------------------------------------------
    # observability (docs/OBSERVABILITY.md)
    # ------------------------------------------------------------------

    def metrics_server_registry(self) -> MetricsRegistry:
        """The SERVER-level registry object (shared with group 0 on the
        single-group plane) — where the health monitor registers the
        ``health.*`` family, so it rides every snapshot un-labeled."""
        return self._metrics

    def health_sample(self) -> dict:
        """Server-scope sample for the health monitor (the per-group
        half is ``RaftGroup.health_sample``): the ingress/proxy plane's
        backlog signals."""
        return {
            "proxy_inflight": self._proxy_inflight,
            "event_backlog": sum(
                len(s.event_queue) for grp in self.groups
                for s in grp.sessions.values()),
        }

    def series_tick(self) -> None:
        """One retained metric sample if due — called from the health
        monitor's tick (the series plane spawns no task of its own;
        ``utils/timeseries.py``). No-op without a series store."""
        if self.series is not None:
            self.series.maybe_sample(self._series_snapshot)

    def _series_snapshot(self) -> dict:
        """What the series ring retains: the merged raft registry (all
        per-group families under ``group=`` labels plus the server
        families — health.*, slo.*, series.* included), with the lazy
        gauges refreshed so role/term/lag are current at the sample."""
        for grp in self.groups:
            grp.refresh_gauges()
        return self.metrics.snapshot()

    def device_flight(self) -> tuple[Any, int]:
        """``(flight ring, current engine round)`` when the server runs
        the TPU executor with an instantiated, telemetry-enabled engine
        (raw ``_engine`` read — never trigger the lazy jit build);
        ``(None, 0)`` otherwise. All groups share one engine
        (docs/SHARDING.md), so group 0's is THE hub."""
        engine = getattr(self.groups[0].state_machine, "_engine", None)
        groups = getattr(engine, "_groups", None)
        hub = getattr(groups, "telemetry", None)
        if hub is None:
            return None, 0
        return hub.flight, getattr(groups, "rounds", 0)

    def _attach_flight_spill(self) -> None:
        """Lazily wire the flight ring's spill to the black-box (the
        engine is built lazily): nemesis faults, invariant violations
        and telemetry notes recorded into the ring then also survive a
        crash. The ONE place the wiring lives — health_note and the
        monitor's tick both route through here."""
        flight, _ = self.device_flight()
        if flight is not None and flight.spill is None \
                and self.blackbox is not None:
            flight.spill = self.blackbox.spill_event

    def health_note(self, kind: str, group: int | None = None,
                    **fields) -> None:
        """Durable health note: into the device flight ring when an
        engine hub exists (its spill forwards to the black-box), else
        straight to the black-box. Never raises — observability must
        never wound the server."""
        try:
            if group is not None:
                fields["group"] = group
            self._attach_flight_spill()
            flight, rounds = self.device_flight()
            if flight is not None:
                flight.record(kind, rounds, **fields)
            elif self.blackbox is not None:
                self.blackbox.record(kind, **fields)
        except Exception:  # noqa: BLE001
            pass

    @property
    def metrics(self) -> MetricsRegistry:
        """The raft registry: group 0's registry object on the
        single-group plane (bit-identical to the pre-refactor server);
        a merged view — per-group families under ``group=`` labels plus
        the server-level ``shard.*`` series — when multi-group."""
        if self.single:
            return self._metrics
        merged = MetricsRegistry()
        for grp in self.groups:
            merged.merge(grp.metrics, group=str(grp.group_id))
        merged.merge(self._metrics)
        return merged

    def stats_snapshot(self) -> dict:
        """Point-in-time stats for the stats listener / ``copycat-tpu
        stats``: refreshes the lazy gauges (term/role/lag/sessions) then
        returns ``{node, role, term, leader, raft, transport?,
        manager?}`` — plus, multi-group, a ``groups`` section (per-group
        role/term/cursors) and the ``shard.*`` series inside ``raft``
        under the server registry."""
        for grp in self.groups:
            grp.refresh_gauges()
        g0 = self.groups[0]
        if not self.single:
            m = self._metrics
            m.gauge("shard.groups").set(self.num_groups)
            m.gauge("shard.groups_led").set(
                sum(1 for g in self.groups if g.role == LEADER))
        snap: dict = {
            "node": str(self.address),
            "role": g0.role,
            "term": g0.term,
            "leader": str(g0.leader_address) if g0.leader_address else None,
            "raft": self.metrics.snapshot(),
        }
        if not self.single:
            snap["groups"] = {
                str(g.group_id): {
                    "role": g.role,
                    "term": g.term,
                    "leader": (str(g.leader_address)
                               if g.leader_address else None),
                    "commit_index": g.commit_index,
                    "last_applied": g.last_applied,
                    "log_last_index": g.log.last_index,
                    "sessions": sum(
                        1 for s in g.sessions.values()
                        if s.state is SessionState.OPEN),
                } for g in self.groups}
        transport_metrics = getattr(self.transport, "metrics", None)
        if transport_metrics is not None:
            snap["transport"] = transport_metrics.snapshot()
        sm_stats = getattr(g0.state_machine, "stats", None)
        if callable(sm_stats):
            snap["manager"] = sm_stats()
        return snap

    # ------------------------------------------------------------------
    # single-group compatibility surface: the pre-refactor RaftServer
    # exposed its per-group state directly; delegate the classic names
    # to group 0 so single-group embedders/tests keep working. Reads of
    # any OTHER group-0 attribute or method fall through __getattr__.
    # ------------------------------------------------------------------

    @property
    def term(self) -> int:
        return self.groups[0].term

    @term.setter
    def term(self, value: int) -> None:
        self.groups[0].term = value

    @property
    def voted_for(self) -> Address | None:
        return self.groups[0].voted_for

    @voted_for.setter
    def voted_for(self, value: Address | None) -> None:
        self.groups[0].voted_for = value

    @property
    def commit_index(self) -> int:
        return self.groups[0].commit_index

    @commit_index.setter
    def commit_index(self, value: int) -> None:
        self.groups[0].commit_index = value

    @property
    def last_applied(self) -> int:
        return self.groups[0].last_applied

    @last_applied.setter
    def last_applied(self, value: int) -> None:
        self.groups[0].last_applied = value

    @property
    def global_index(self) -> int:
        return self.groups[0].global_index

    @global_index.setter
    def global_index(self, value: int) -> None:
        self.groups[0].global_index = value

    @property
    def role(self) -> str:
        return self.groups[0].role

    @role.setter
    def role(self, value: str) -> None:
        self.groups[0].role = value

    @property
    def leader_address(self) -> Address | None:
        return self.groups[0].leader_address

    @leader_address.setter
    def leader_address(self, value: Address | None) -> None:
        self.groups[0].leader_address = value

    @property
    def members(self) -> list[Address]:
        return self.groups[0].members

    @members.setter
    def members(self, value: list[Address]) -> None:
        for grp in self.groups:
            grp.members = list(value)

    @property
    def log(self):
        return self.groups[0].log

    @property
    def sessions(self) -> dict:
        return self.groups[0].sessions

    @property
    def state_machine(self) -> StateMachine:
        return self.groups[0].state_machine

    @property
    def executor(self):
        return self.groups[0].executor

    @property
    def context(self):
        return self.groups[0].context

    @property
    def _snap_index(self) -> int:
        return self.groups[0]._snap_index

    @_snap_index.setter
    def _snap_index(self, value: int) -> None:
        self.groups[0]._snap_index = value

    def __getattr__(self, item: str):
        # delegation fallback for the classic single-group surface
        # (methods and leader-volatile dicts live on the group now);
        # guarded so a missing attribute during __init__ cannot recurse
        if item.startswith("__"):
            raise AttributeError(item)
        groups = self.__dict__.get("groups")
        if not groups:
            raise AttributeError(item)
        return getattr(groups[0], item)
