"""The replicated log: entries, segmented storage, incremental cleaning.

The reference's storage contract (SURVEY.md §5.4): no snapshots — live state is
*retained commits*; every applied commit must eventually be ``clean()``ed
(effect superseded; entry reclaimable) and compaction drops cleaned entries.
``Storage(StorageLevel.MEMORY|MAPPED|DISK, max_entries_per_segment, ...)``
mirrors the reference builder surface (``withMaxEntriesPerSegment(16)`` in
``StandaloneServerExample.java``).

The TPU engine's equivalent of this file is a fixed-capacity ring + liveness
bitmap per group (``copycat_tpu.ops.logring``); this CPU log is the oracle.
"""

from __future__ import annotations

import enum
import json
import logging
import mmap
import os
import zlib
from typing import Any, Iterator

from ..io.buffer import BufferInput, BufferOutput
from ..io.serializer import Serializer, serialize_with
from ..utils.fields import compile_field_init


class StorageLevel(enum.Enum):
    MEMORY = "memory"
    MAPPED = "mapped"  # mmap-backed segments (page-cache writes, no syscalls)
    DISK = "disk"      # buffered files, flushed (not fsynced) per append


#: Valid ``Storage.fsync`` policies (docs/DURABILITY.md):
#: - "never":  buffered flush per append only; data reaches the disk at the
#:   OS's leisure (or at ``close()``). Survives process crash, not power loss.
#: - "commit": ``Log.sync()`` fsyncs/msyncs at every point an entry becomes
#:   part of the commit contract — when the server's commit index advances,
#:   on followers BEFORE a success AppendResponse (the leader counts that
#:   ack toward quorum commit; an un-fsynced ack could let a cluster-wide
#:   power loss erase an acknowledged commit), and at segment-roll
#:   boundaries — the default: committed (acknowledged) entries are
#:   power-loss durable, uncommitted tail entries may be torn (which Raft
#:   recovery tolerates by construction).
#: - "always": fsync/msync per appended entry. Strongest and slowest.
FSYNC_POLICIES = ("never", "commit", "always")


class Storage:
    """Log storage configuration (reference ``Storage`` builder equivalent).

    Actual durability of each level (measured against a process crash /
    a power loss, with the default ``fsync="commit"`` policy):

    ============ ======================= ==================================
    level        process crash           power loss / kernel crash
    ============ ======================= ==================================
    ``MEMORY``   lost (no files)         lost
    ``MAPPED``   safe (page cache)       committed prefix safe after
                                         ``sync()``; torn tail dropped by
                                         the per-frame seeded CRC
    ``DISK``     safe (flushed)          committed prefix safe after
                                         ``sync()``; torn tail dropped by
                                         the length-framed replay walk
    ============ ======================= ==================================

    ``fsync="never"`` downgrades the power-loss column to "lost since the
    last roll/close"; ``fsync="always"`` upgrades it to per-entry at the
    cost of one fsync/msync per append.
    """

    def __init__(
        self,
        level: StorageLevel = StorageLevel.MEMORY,
        directory: str | None = None,
        max_entries_per_segment: int = 1024,
        compaction_threshold: float = 0.5,
        fsync: str = "commit",
    ) -> None:
        if fsync not in FSYNC_POLICIES:
            raise ValueError(f"fsync must be one of {FSYNC_POLICIES}, got {fsync!r}")
        self.level = level
        self.directory = directory
        self.max_entries_per_segment = max_entries_per_segment
        self.compaction_threshold = compaction_threshold
        self.fsync = fsync

    def build_log(self, name: str = "log") -> "Log":
        return Log(self, name)


class _MappedSegment:
    """One mmap-backed log segment: ``[u64 watermark][frames...]``.

    The MAPPED level of the reference Storage contract: appends are memory
    copies into the OS page cache through the mapping — no write/flush
    syscall per entry (DISK pays both).  Durability is page-cache-deep until
    ``close()`` (which msyncs).  Kernel writeback order between the
    watermark page and frame pages is unspecified, so the watermark alone
    cannot bound a torn tail; each frame therefore carries
    ``[u32 len][u32 crc32]`` and recovery stops at the first frame whose
    checksum fails — everything before it is intact by construction.
    """

    HEADER = 8
    FRAME_HEADER = 8  # u32 payload length + u32 crc32
    #: Nonzero CRC seed: crc32(b"") == 0, so with a zero seed an all-zero
    #: torn frame (header page never written back) would VALIDATE as an
    #: empty frame. Seeding makes all-zero bytes fail the check.
    #: The seed also doubles as the entry WIRE-FORMAT version stamp: bump
    #: it whenever serialized entry bytes OR the segment framing change
    #: shape (last: the trailing per-frame CRC added to DISK segments —
    #: shared seed, so pre-CRC .seg files fail the check at their first
    #: frame instead of misparsing the next frame's length as a CRC), so
    #: segments written by an older format fail CRC cleanly at frame 0
    #: and recover as empty instead of misparsing old bytes into wrong
    #: entries.
    CRC_SEED = 0xA5C6

    def __init__(self, path: str, capacity: int) -> None:
        # Exclusive create: segments are named by the entry index that
        # triggered the roll, so an unexpected name collision must fail
        # loudly instead of silently truncating persisted frames (the DISK
        # path is immune via "ab"; this keeps MAPPED equally safe).
        self._f = open(path, "x+b")
        self._f.truncate(self.HEADER + capacity)
        self._mm = mmap.mmap(self._f.fileno(), 0)
        self._used = 0

    @classmethod
    def reopen(cls, path: str) -> "_MappedSegment":
        """Reopen an existing segment for continued appends after recovery:
        the write position resumes after the last CRC-valid frame and the
        watermark is re-clamped to it.

        The region between the resume point and the old watermark is
        ZEROED AND FLUSHED before any append: it may still hold CRC-valid
        stale frames (e.g. a torn tail the recovery discarded), and a later
        crash whose writeback persisted an advanced watermark but not the
        new frame bytes would otherwise resurrect them as a log prefix
        that never existed (the same writeback-reordering class the CRC
        framing defends against)."""
        seg = cls.__new__(cls)
        seg._f = open(path, "r+b")
        seg._mm = mmap.mmap(seg._f.fileno(), 0)
        old_mark = int.from_bytes(seg._mm[:cls.HEADER], "little")
        used = 0
        for payload in cls.read_payloads(path):
            used += cls.FRAME_HEADER + len(payload)
        seg._used = used
        seg._mm[:cls.HEADER] = used.to_bytes(cls.HEADER, "little")
        stale_end = min(cls.HEADER + old_mark, len(seg._mm))
        if stale_end > cls.HEADER + used:
            seg._mm[cls.HEADER + used:stale_end] = bytes(
                stale_end - cls.HEADER - used)
        seg._mm.flush()  # stale bytes must be gone before any new frame
        return seg

    def append(self, payload: bytes) -> bool:
        """Copy a frame in; False when it doesn't fit (caller rolls over)."""
        start = self.HEADER + self._used
        total = self.FRAME_HEADER + len(payload)
        if start + total > len(self._mm):
            return False
        header = (len(payload).to_bytes(4, "little")
                  + zlib.crc32(payload, self.CRC_SEED).to_bytes(4, "little"))
        self._mm[start:start + total] = header + payload
        self._used += total
        self._mm[:self.HEADER] = self._used.to_bytes(self.HEADER, "little")
        return True

    def flush(self) -> None:
        """msync the mapping: everything appended so far is power-loss
        durable (the MAPPED half of the ``fsync`` policy)."""
        self._mm.flush()

    def close(self) -> None:
        self._mm.flush()
        self._mm.close()
        self._f.close()

    @staticmethod
    def read_payloads(path: str) -> list[bytes]:
        """CRC-valid frame payloads of a closed/crashed segment, stopping
        at the first torn frame (watermark- and checksum-bounded)."""
        return _MappedSegment.read_payloads_ex(path)[0]

    @staticmethod
    def read_payloads_ex(path: str) -> tuple[list[bytes], bool]:
        """``(payloads, torn)``: the CRC-valid frame payloads plus whether
        the walk stopped BEFORE the watermark (a torn frame inside the
        written region — recovery must then distrust everything after
        this segment, not just this segment's tail)."""
        with open(path, "rb") as f:
            used = int.from_bytes(f.read(_MappedSegment.HEADER), "little")
            data = f.read(used)
        payloads = []
        pos = 0
        torn = len(data) < used
        while pos + _MappedSegment.FRAME_HEADER <= len(data):
            length = int.from_bytes(data[pos:pos + 4], "little")
            crc = int.from_bytes(data[pos + 4:pos + 8], "little")
            payload = data[pos + 8:pos + 8 + length]
            # The seeded CRC alone separates "torn" from "empty":
            # crc32(b"", CRC_SEED) != 0, so an all-zero torn frame fails
            # while a legitimately zero-length payload still validates.
            if (len(payload) < length
                    or zlib.crc32(payload, _MappedSegment.CRC_SEED) != crc):
                torn = True
                break  # torn tail: everything before it is intact
            payloads.append(payload)
            pos += _MappedSegment.FRAME_HEADER + length
        return payloads, torn or pos < used


class Entry(object):
    """Base log entry. ``index`` is assigned on append; ``timestamp`` is the
    leader's clock at append time and drives all deterministic timers."""

    _fields: tuple[str, ...] = ()

    def __init__(self, term: int = 0, timestamp: float = 0.0, **kwargs: Any) -> None:
        self.index = 0
        self.term = term
        self.timestamp = timestamp
        for name in self._fields:
            setattr(self, name, kwargs.get(name))

    def __init_subclass__(cls, **kwargs: Any) -> None:
        # Compiled per-class __init__ (same treatment as protocol
        # messages): CommandEntry construction is per-op on the leader's
        # append path, where the generic kwargs loop was measurable.
        super().__init_subclass__(**kwargs)
        fields = cls.__dict__.get("_fields")
        if fields is None or "__init__" in cls.__dict__:
            return
        compile_field_init(cls, fields,
                           head=", term=0, timestamp=0.0",
                           body_head="    self.index = 0\n"
                                     "    self.term = term\n"
                                     "    self.timestamp = timestamp\n")

    def write_object(self, buf: BufferOutput, serializer: Serializer) -> None:
        buf.write_i64(self.index)
        buf.write_i64(self.term)
        buf.write_f64(self.timestamp)
        for name in self._fields:
            serializer.write_object(getattr(self, name), buf)

    def read_object(self, buf: BufferInput, serializer: Serializer) -> None:
        self.index = buf.read_i64()
        self.term = buf.read_i64()
        self.timestamp = buf.read_f64()
        for name in self._fields:
            setattr(self, name, serializer.read_object(buf))

    def __repr__(self) -> str:
        inner = ", ".join(f"{n}={getattr(self, n)!r}" for n in self._fields)
        return f"{type(self).__name__}(i={self.index}, t={self.term}{', ' if inner else ''}{inner})"


@serialize_with(230)
class NoOpEntry(Entry):
    """Appended by a new leader to commit entries from prior terms and to
    advance the deterministic state-machine clock (drives log-time timers)."""


@serialize_with(231)
class RegisterEntry(Entry):
    # session_id: None on the single-group plane (the id IS the entry's
    # log index, the reference rule). On a multi-group server the
    # id-allocating group 0 leaves it None and derives the global id at
    # apply; the fan-out entries appended to groups 1..G-1 carry that id
    # explicitly so every group's replica shares it (docs/SHARDING.md).
    _fields = ("client_id", "timeout", "session_id")


@serialize_with(232)
class KeepAliveEntry(Entry):
    _fields = ("session_id", "command_seq", "event_index")


@serialize_with(233)
class UnregisterEntry(Entry):
    # expired=True when appended by the leader's session-timeout detector;
    # False for a graceful client unregister.
    _fields = ("session_id", "expired")


@serialize_with(234)
class CommandEntry(Entry):
    _fields = ("session_id", "seq", "operation")


@serialize_with(235)
class ConfigurationEntry(Entry):
    _fields = ("members",)


class Log:
    """Append-ordered entry store with incremental cleaning.

    In-memory list with a base offset; DISK/MAPPED levels additionally append
    serialized entries to segment files and recover by replay on open.
    ``clean(index)`` marks an entry's effect superseded; ``compact()`` nulls
    cleaned entries that every server has applied (they are never sent again),
    freeing memory while preserving indices.

    ``truncate_prefix(index)`` actually RELEASES the prefix behind a
    state-machine snapshot (docs/DURABILITY.md): entries ``<= index`` are
    dropped, fully-covered segment files are deleted, and
    ``(prefix_index, prefix_term)`` is persisted in an atomic marker file so
    recovery replays only the surviving tail.  ``term_at(prefix_index)``
    keeps answering from the marker — AppendEntries consistency checks and
    vote up-to-date comparisons still work at the truncation boundary.
    """

    def __init__(self, storage: Storage, name: str = "log") -> None:
        self._storage = storage
        self._name = name
        self._entries: list[Entry | None] = []
        self._offset = 1  # index of _entries[0]
        # last index released by prefix truncation (0 = none) and its term;
        # everything <= _prefix_index lives only in the snapshot now.
        self._prefix_index = 0
        self._prefix_term = 0
        self._cleaned: set[int] = set()
        # (start_index, term) for each term change — lets term_at() answer for
        # compacted (None) slots, which matters for AppendEntries prev-term
        # checks and vote up-to-date comparisons after compaction.
        self._term_starts: list[tuple[int, int]] = []
        self._serializer = Serializer()
        self._segment_file = None          # DISK: buffered append file
        self._mapped: _MappedSegment | None = None  # MAPPED: mmap segment
        self._segment_count = 0
        if storage.level in (StorageLevel.DISK, StorageLevel.MAPPED):
            assert storage.directory, "DISK/MAPPED storage requires a directory"
            os.makedirs(storage.directory, exist_ok=True)
            self._recover()

    # -- append/read -------------------------------------------------------

    @property
    def first_index(self) -> int:
        return self._offset

    @property
    def prefix_index(self) -> int:
        """Last index released by prefix truncation (0 = nothing released).
        A follower whose ``next_index`` falls at or below this cannot be
        served from the log — it needs a snapshot install."""
        return self._prefix_index

    @property
    def prefix_term(self) -> int:
        return self._prefix_term

    @property
    def last_index(self) -> int:
        return self._offset + len(self._entries) - 1

    @property
    def empty(self) -> bool:
        return not self._entries

    def _note_term(self, index: int, term: int) -> None:
        if not self._term_starts or self._term_starts[-1][1] != term:
            if not self._term_starts or self._term_starts[-1][0] < index:
                self._term_starts.append((index, term))

    def append(self, entry: Entry) -> int:
        entry.index = self.last_index + 1
        self._entries.append(entry)
        self._note_term(entry.index, entry.term)
        if self._segment_dir is not None:
            self._persist(entry)
        return entry.index

    def append_block(self, entries: list[Entry]) -> int:
        """Append a run of same-term stamped entries with one index walk
        (the leader's batched command staging); returns the last index."""
        if not entries:
            return self.last_index
        index = self.last_index
        store = self._entries
        for entry in entries:
            index += 1
            entry.index = index
            store.append(entry)
        self._note_term(entries[0].index, entries[0].term)
        if self._segment_dir is not None:
            for entry in entries:
                self._persist(entry)
        return index

    def append_replicated(self, entry: Entry) -> None:
        """Append an entry at its replicated index, gap-filling compacted
        slots with None (a leader may legitimately skip cleaned+compacted
        entries when replicating — their effects are superseded by design)."""
        assert entry.index > self.last_index, f"{entry.index} <= {self.last_index}"
        while self.last_index + 1 < entry.index:
            self._entries.append(None)
        self._entries.append(entry)
        self._note_term(entry.index, entry.term)
        if self._segment_dir is not None:
            self._persist(entry)

    def append_replicated_block(self, entries: list[Entry]) -> None:
        """Append a run of replicated entries past ``last_index`` in one
        walk — the follower's mirror of the leader's ``append_block``.

        Gap-fills compacted slots between entries (same contract as
        ``append_replicated``), notes term boundaries once per term
        change instead of per entry, and persists the whole block after
        the in-memory walk. Entries must arrive in increasing index
        order starting past the current tail (the shape one
        AppendRequest window has after the conflict scan)."""
        if not entries:
            return
        assert entries[0].index > self.last_index, \
            f"{entries[0].index} <= {self.last_index}"
        store = self._entries
        index = self.last_index
        term = self._term_starts[-1][1] if self._term_starts else None
        for entry in entries:
            while index + 1 < entry.index:
                store.append(None)
                index += 1
            store.append(entry)
            index += 1
            if entry.term != term:
                self._note_term(entry.index, entry.term)
                term = entry.term
        if self._segment_dir is not None:
            for entry in entries:
                self._persist(entry)

    def fill_gap(self, to_index: int) -> None:
        """Extend the log with empty (compacted-elsewhere) slots up to to_index."""
        while self.last_index < to_index:
            self._entries.append(None)

    def set_slot(self, entry: Entry) -> None:
        """Place an entry into a previously gap-filled (None) slot."""
        slot = entry.index - self._offset
        if 0 <= slot < len(self._entries) and self._entries[slot] is None:
            self._entries[slot] = entry
            if self._segment_dir is not None:
                self._persist(entry)

    def get(self, index: int) -> Entry | None:
        if index < self._offset or index > self.last_index:
            return None
        return self._entries[index - self._offset]

    def entries_from(self, index: int, limit: int = 64) -> list[Entry]:
        """Entries [index, index+limit) for replication. Compacted (None) slots
        are skipped — they are only compacted once all members applied them."""
        out = []
        for i in range(max(index, self._offset), min(index + limit, self.last_index + 1)):
            entry = self._entries[i - self._offset]
            if entry is not None:
                out.append(entry)
        return out

    def truncate(self, from_index: int) -> None:
        """Remove entries >= from_index (conflict resolution on followers)."""
        if from_index <= self.last_index:
            keep = max(0, from_index - self._offset)
            self._entries = self._entries[:keep]
            self._cleaned = {i for i in self._cleaned if i < from_index}
            self._term_starts = [(i, t) for i, t in self._term_starts if i < from_index]
            if self._segment_dir is not None:
                self._persist_truncate(from_index)

    def term_at(self, index: int) -> int:
        """Term of the entry at index; falls back to term-boundary tracking for
        compacted slots. 0 means unknown (empty log, out of range, or a
        gap-filled slot whose term was never seen)."""
        entry = self.get(index)
        if entry is not None:
            return entry.term
        if index == self._prefix_index:
            return self._prefix_term  # the snapshot boundary entry's term
        if index < self._offset or index > self.last_index:
            return 0
        term = 0
        for start, t in self._term_starts:
            if start <= index:
                term = t
            else:
                break
        return term

    def __iter__(self) -> Iterator[Entry]:
        return (e for e in self._entries if e is not None)

    def __len__(self) -> int:
        return len(self._entries)

    # -- cleaning / compaction --------------------------------------------

    def clean(self, index: int) -> None:
        self._cleaned.add(index)

    def is_cleaned(self, index: int) -> bool:
        return index in self._cleaned

    @property
    def cleaned_count(self) -> int:
        return len(self._cleaned)

    def compact(self, global_index: int) -> int:
        """Null out cleaned entries with index <= global_index (the minimum
        index applied on ALL servers).  Returns the number reclaimed."""
        reclaimed = 0
        for index in [i for i in self._cleaned if i <= global_index]:
            slot = index - self._offset
            if 0 <= slot < len(self._entries) and self._entries[slot] is not None:
                self._entries[slot] = None
                reclaimed += 1
            self._cleaned.discard(index)
        return reclaimed

    # -- prefix truncation (snapshot plane, docs/DURABILITY.md) ------------

    def truncate_prefix(self, to_index: int) -> int:
        """Release entries ``<= to_index`` behind a state-machine snapshot;
        returns the number of live entries dropped.  Unlike ``compact()``
        (which nulls slots but keeps the index range), this moves the log's
        base: recovery replays only the surviving tail, and segment files
        wholly behind the boundary are deleted from disk."""
        to_index = min(to_index, self.last_index)
        if to_index < self._offset:
            return 0
        drop = to_index - self._offset + 1
        released = sum(1 for e in self._entries[:drop] if e is not None)
        # the boundary term BEFORE dropping the entries that know it
        prefix_term = self.term_at(to_index)
        first_term = self.term_at(to_index + 1) if to_index < self.last_index else 0
        del self._entries[:drop]
        self._offset = to_index + 1
        self._prefix_index = to_index
        self._prefix_term = prefix_term
        self._cleaned = {i for i in self._cleaned if i > to_index}
        self._term_starts = [(i, t) for i, t in self._term_starts if i > to_index]
        if self._entries and first_term and (
                not self._term_starts or self._term_starts[0][0] > self._offset):
            self._term_starts.insert(0, (self._offset, first_term))
        if self._segment_dir is not None:
            self._persist_prefix()
            self._drop_covered_segments(to_index)
        return released

    def reset_to(self, index: int, term: int) -> None:
        """Discard the ENTIRE log and restart it just past ``index`` (a
        snapshot install whose boundary the local log cannot match): the
        snapshot is committed state, so everything local — including any
        conflicting tail — is superseded or will be re-replicated."""
        self._entries = []
        self._offset = index + 1
        self._prefix_index = index
        self._prefix_term = term
        self._cleaned = set()
        self._term_starts = []
        if self._segment_dir is not None:
            self.close()
            for fname in os.listdir(self._segment_dir):
                if fname.startswith(f"{self._name}-") and fname.endswith((".seg", ".mseg")):
                    os.remove(os.path.join(self._segment_dir, fname))
            self._segment_count = 0
            self._persist_prefix()

    def _segment_starts(self) -> list[tuple[int, str]]:
        """(first entry index, path) of every segment file, ascending."""
        out = []
        for fname in os.listdir(self._segment_dir):
            if not fname.startswith(f"{self._name}-"):
                continue
            stem, _, ext = fname.rpartition(".")
            if ext in ("seg", "mseg"):
                out.append((int(stem[len(self._name) + 1:]),
                            os.path.join(self._segment_dir, fname)))
        return sorted(out)

    def _drop_covered_segments(self, to_index: int) -> None:
        """Delete segment files whose every entry is ``<= to_index``.  A
        segment's coverage ends where the next one starts, so the newest
        (active) segment is never deleted and partially-covered segments
        stay — recovery skips their below-prefix entries via the marker."""
        starts = self._segment_starts()
        for k, (_, path) in enumerate(starts[:-1]):
            if starts[k + 1][0] <= to_index + 1:
                os.remove(path)

    def sync(self) -> None:
        """Force appended entries to stable storage (fsync/msync) — the
        ``fsync="commit"`` policy's durability point, called by the server
        whenever its commit index advances."""
        if self._segment_file is not None:
            self._segment_file.flush()
            os.fsync(self._segment_file.fileno())
        if self._mapped is not None:
            self._mapped.flush()

    # -- disk persistence --------------------------------------------------

    @property
    def _segment_dir(self) -> str | None:
        if self._storage.level in (StorageLevel.DISK, StorageLevel.MAPPED):
            return self._storage.directory
        return None

    #: MAPPED segment capacity (frame bytes; oversize frames get their own
    #: segment).  Small segments keep the reference's roll-over semantics
    #: (``withMaxEntriesPerSegment``) observable in tests.
    MAPPED_SEGMENT_BYTES = 1 << 16

    def _segment_path(self, index: int) -> str:
        ext = "mseg" if self._storage.level is StorageLevel.MAPPED else "seg"
        return os.path.join(self._segment_dir, f"{self._name}-{index}.{ext}")

    def _persist(self, entry: Entry) -> None:
        data = self._serializer.write(entry)
        if self._storage.level is StorageLevel.MAPPED:
            roll = (self._mapped is None
                    or self._segment_count >= self._storage.max_entries_per_segment)
            if not roll and not self._mapped.append(data):
                roll = True  # full: close and start a segment that fits
            if roll:
                if self._mapped is not None:
                    self._mapped.close()  # close() msyncs: rolls are durable
                self._mapped = _MappedSegment(
                    self._segment_path(entry.index),
                    max(self.MAPPED_SEGMENT_BYTES,
                        _MappedSegment.FRAME_HEADER + len(data)))
                self._segment_count = 0
                if not self._mapped.append(data):
                    raise AssertionError("fresh mapped segment rejected frame")
            self._segment_count += 1
            if self._storage.fsync == "always":
                self._mapped.flush()
            return
        # [varint len][payload][varint crc32(payload, seed)]: the trailing
        # seeded CRC catches torn frames whose LENGTH survived — without
        # it, a zeroed/garbled payload tail can deserialize into a
        # plausible-but-wrong entry and silently corrupt the state
        # machine on replay (found by the partial_frame nemesis).
        frame = (BufferOutput().write_bytes(data)
                 .write_varint(zlib.crc32(data, _MappedSegment.CRC_SEED))
                 .to_bytes())
        if self._segment_file is None or self._segment_count >= self._storage.max_entries_per_segment:
            if self._segment_file is not None:
                if self._storage.fsync != "never":
                    # segment-roll boundary: the closed segment is durable
                    self._segment_file.flush()
                    os.fsync(self._segment_file.fileno())
                self._segment_file.close()
            self._segment_file = open(self._segment_path(entry.index), "ab")
            self._segment_count = 0
        self._segment_file.write(frame)
        self._segment_file.flush()
        if self._storage.fsync == "always":
            os.fsync(self._segment_file.fileno())
        self._segment_count += 1

    def _persist_truncate(self, from_index: int) -> None:
        # Truncation is rare (follower conflict resolution): rewrite all
        # segments from the surviving in-memory entries.
        self.close()
        for fname in os.listdir(self._segment_dir):
            if fname.startswith(f"{self._name}-") and fname.endswith((".seg", ".mseg")):
                os.remove(os.path.join(self._segment_dir, fname))
        self._segment_count = 0
        for entry in self._entries:
            if entry is not None:
                self._persist(entry)

    @property
    def _prefix_path(self) -> str:
        return os.path.join(self._segment_dir, f"{self._name}.trunc")

    def _persist_prefix(self) -> None:
        """Atomically persist the prefix-truncation marker (CRC-framed so a
        torn marker is detected, tmp+fsync+rename so it never half-writes)."""
        from . import snapshot as snapfile
        payload = json.dumps({"index": self._prefix_index,
                              "term": self._prefix_term}).encode()
        snapfile.write_atomic(self._prefix_path, snapfile.frame(payload))

    def _load_prefix(self) -> None:
        from . import snapshot as snapfile
        path = self._prefix_path
        if not os.path.exists(path):
            return
        try:
            with open(path, "rb") as f:
                payload = snapfile.unframe(f.read())
        except OSError:  # pragma: no cover - unreadable marker
            payload = None
        if payload is None:
            # A corrupt marker is tolerable: segments behind the (lost)
            # boundary were deleted, so replay just gap-fills None slots
            # below the snapshot index and apply skips them.
            logging.getLogger(__name__).warning(
                "prefix marker %s corrupt; recovering without it", path)
            return
        meta = json.loads(payload.decode())
        self._prefix_index = int(meta["index"])
        self._prefix_term = int(meta["term"])
        self._offset = self._prefix_index + 1

    def _recover(self) -> None:
        directory = self._storage.directory
        self._load_prefix()
        log = logging.getLogger(__name__)
        segments = []
        for fname in os.listdir(directory):
            if not fname.startswith(f"{self._name}-"):
                continue
            stem, dot, ext = fname.rpartition(".")
            if ext in ("seg", "mseg"):
                segments.append((int(stem[len(self._name) + 1:]), fname, ext))
        last_path = last_ext = None
        last_count = 0
        torn = False
        for _, fname, ext in sorted(segments):
            path = os.path.join(directory, fname)
            if torn:
                # everything past a torn point is suspect: a gap in the
                # entry sequence must never recover as silent None slots
                # (replication would log-match right past them) — drop the
                # orphaned segment; its entries re-replicate from the
                # leader like any truncated tail
                log.warning("log segment %s is past a torn frame; "
                            "dropping it", path)
                os.remove(path)
                continue
            if ext == "mseg":
                payloads, seg_torn = _MappedSegment.read_payloads_ex(path)
                frame_ends = None
            else:
                with open(path, "rb") as f:
                    raw = f.read()
                buf = BufferInput(raw)
                payloads = []
                frame_ends = []  # byte offset after each intact frame
                seg_torn = False
                while buf.remaining > 0:
                    try:
                        payload = buf.read_bytes()
                        crc = buf.read_varint()
                    except EOFError:
                        # torn tail (crash mid-append / dropped buffered
                        # write): everything before it is intact — the
                        # length-framed walk is sequential
                        seg_torn = True
                        break
                    if zlib.crc32(payload, _MappedSegment.CRC_SEED) != crc:
                        seg_torn = True
                        break
                    payloads.append(payload)
                    frame_ends.append(len(raw) - buf.remaining)
            # decode; an undecodable payload is a torn frame too (the
            # DISK format is length-framed without a per-frame CRC)
            entries = []
            for k, payload in enumerate(payloads):
                try:
                    entries.append(self._serializer.read(payload))
                except Exception:  # noqa: BLE001 - corrupt frame payload
                    seg_torn = True
                    payloads = payloads[:k]
                    break
            if seg_torn:
                torn = True
                log.warning(
                    "log segment %s has a torn/corrupt frame; recovering "
                    "the %d intact entries before it", path, len(entries))
                if ext == "seg":
                    # drop the torn bytes so continued appends never land
                    # after garbage (the MAPPED reopen() zeroes its stale
                    # region for the same reason)
                    keep = frame_ends[len(payloads) - 1] if payloads else 0
                    with open(path, "r+b") as f:
                        f.truncate(keep)
            last_path, last_ext, last_count = path, ext, len(payloads)
            for entry in entries:
                if entry.index <= self._prefix_index:
                    # a partially-covered segment: its low entries are
                    # behind the snapshot boundary and already released
                    continue
                # Replayed entries keep their persisted indices.  Gap-filled
                # (compacted-elsewhere) slots were never persisted, so recovery
                # re-creates the gaps as None slots.
                if entry.index > self.last_index:
                    while self.last_index + 1 < entry.index:
                        self._entries.append(None)
                    self._entries.append(entry)
                else:
                    # Overwrite (post-truncate rewrite)
                    self._entries[entry.index - self._offset] = entry
                self._note_term(entry.index, entry.term)
        # Reopen the newest segment for continued appends so repeated
        # restarts don't accumulate one near-empty segment per run.
        if last_path is not None \
                and last_count < self._storage.max_entries_per_segment:
            if last_ext == "mseg":
                self._mapped = _MappedSegment.reopen(last_path)
            else:
                self._segment_file = open(last_path, "ab")
            self._segment_count = last_count

    def close(self) -> None:
        if self._segment_file is not None:
            self._segment_file.close()
            self._segment_file = None
        if self._mapped is not None:
            self._mapped.close()
            self._mapped = None
