"""RaftGroup: one Raft consensus group inside a (possibly multi-group) server.

The multi-raft keyspace-sharding refactor (docs/SHARDING.md) moved every
piece of per-group mutable state out of ``RaftServer`` into this class:
term, vote, log, commit/apply cursors, role, election/heartbeat timers,
the replication streams, the session plane, the snapshot store and the
apply loop all live HERE, once per group. ``RaftServer`` (server/raft.py)
keeps what is genuinely shared — the transport, the peer connection pool,
the ingress routing/proxy plane, and the stats surface — and hosts N of
these objects. With ``groups=1`` (the default, and the forced shape under
``COPYCAT_MULTI_GROUP=0``) exactly one group exists and every method in
this file behaves bit-identically to the pre-refactor single-group
server: wire messages carry ``group=None``, event gating/session staging
take the legacy branches, and the election timer keeps the legacy
``uniform(T, 2T)`` distribution.

Multi-group additions are deliberately concentrated:

- every server<->server RPC this group sends stamps ``group=`` so the
  server-side dispatch can demultiplex per-group streams over the same
  correlated peer connections;
- ``_reset_election_timer`` biases the timeout by this member's
  deterministic preference rank for the group (seed-spread leadership:
  member ``g % N`` fires first and wins at boot; on leader loss the next
  live rank tends to win — rebalance-on-timeout);
- ``command_block``/``keepalive_local``/``register_local``/
  ``serve_query`` are the group-scoped staging entry points the
  multi-group ingress (local or proxied) calls — they accept the GAPPED
  per-group seq subsequences hash routing produces, where the legacy
  handlers require the dense single-group sequence;
- ``_seal_and_push`` gates event push on ``session.connection`` instead
  of leadership when multi-group: the member holding the client's
  connection (the ingress) pushes events from its own follower apply,
  because the group's leader may be a different member.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import random
import time
from typing import Any

from ..io.serializer import Serializer
from ..io.transport import Address, Connection, TransportError
from ..protocol import messages as msg
from ..protocol.operations import Command, CommandConsistency, QueryConsistency
from ..utils import knobs
from ..utils.scheduled import Scheduled
from ..utils.tasks import spawn
from ..utils.tracing import TRACER
from .log import (
    CommandEntry,
    ConfigurationEntry,
    Entry,
    KeepAliveEntry,
    NoOpEntry,
    RegisterEntry,
    UnregisterEntry,
)
from .session import ServerSession, SessionState
from .snapshot import SnapshotStore, write_atomic
from .state_machine import Commit, StateMachine, StateMachineExecutor

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"

#: edge delta record state marking a version-refresh (the resource is
#: unchanged at the record's version — docs/EDGE_READS.md); the client
#: bumps the entry's version/TTL without touching its state
_EDGE_REFRESH = ("r", None)

logger = logging.getLogger(__name__)


def dispatch_vector_rows(engine: Any, window: Any, rows: list
                         ) -> tuple[list, str | None]:
    """ONE engine round for ``rows`` (staged vector-lane tuples, clock
    first): drain the window's in-flight generator chains so device-op
    order follows the log, marshal the rows into ``run_vector`` columns,
    dispatch. Returns ``(raws, pump_error)`` — a barrier or pump failure
    yields empty ``raws`` with the error set, for the caller's explicit
    per-entry failure branch (:meth:`RaftGroup._finalize_vector_run`).
    The ONE marshalling of the staged row shape, shared by the per-group
    lane (``RaftGroup._apply_vector_run``) and the server's fused
    cross-group dispatch (``RaftServer._flush_fused_engine``)."""
    if window is not None and window.busy:
        try:
            window.barrier()
        except Exception as e:  # noqa: BLE001 — fail rows, not hang
            logger.exception("window drain before vector dispatch failed")
            return [], str(e)
    n = len(rows)
    groups_idx = [0] * n
    opc = [0] * n
    av = [0] * n
    bv = [0] * n
    cv = [0] * n
    for k, (_clock, _e, _s, machine, _i, _op, spec) in enumerate(rows):
        groups_idx[k] = machine._group
        opc[k], av[k], bv[k], cv[k] = spec[0], spec[1], spec[2], spec[3]
    try:
        return engine.run_vector(groups_idx, opc, av, bv, cv), None
    except Exception as e:  # liveness failure: fail loudly, not hang
        logger.exception("vector pump failed; failing %d rows", n)
        return [], str(e)


class _EntryCtx:
    """Per-entry execution context for windowed applies.

    While entered, session publishes are buffered (replayed in log order
    at the entry's finalization) and the executor context's clock/index
    are pinned to the ENTRY's values — a deferred chain resumes after
    later entries advanced the clock, and timers it schedules must use the
    entry's log time on every server or TTL firing order would diverge
    between replicas with different commit-batch boundaries.
    """

    __slots__ = ("raft", "index", "clock", "touched", "buffer", "trace",
                 "_prev_touched", "_prev_buffer", "_prev_index",
                 "_prev_clock")

    def __init__(self, raft: "RaftGroup", entry: Entry,
                 trace: int | None = None) -> None:
        self.raft = raft
        self.index = entry.index
        # _apply_entry already advanced context.clock to this entry
        self.clock = raft.context.clock
        self.touched: set = set()
        self.buffer: list = []
        # originating trace id for event-push attribution at
        # finalization (the causal-tracing plane; None when untraced)
        self.trace = trace

    def __enter__(self) -> "_EntryCtx":
        r = self.raft
        self._prev_touched = r._touched_sessions
        self._prev_buffer = r._publish_buffer
        self._prev_index = r.context.index
        self._prev_clock = r.context.clock
        r._touched_sessions = self.touched
        r._publish_buffer = self.buffer
        r.context.index = self.index
        r.context.clock = self.clock
        return self

    def __exit__(self, *exc) -> None:
        r = self.raft
        r._touched_sessions = self._prev_touched
        r._publish_buffer = self._prev_buffer
        r.context.index = self._prev_index
        r.context.clock = self._prev_clock

    def replay(self) -> None:
        """Flush buffered publishes into the session event queues."""
        for orig, event, message, session in self.buffer:
            orig(event, message)
            self.touched.add(session)
        self.buffer.clear()


class _PeerStream:
    """Leader-side state for one follower's pipelined replication stream.

    The pipeline keeps up to ``COPYCAT_REPL_DEPTH`` append windows in
    flight over the peer connection's correlated multiplexing; this
    object tracks the in-flight accounting (windows + entries, the
    backpressure currency), the rewind ``epoch`` (bumped whenever a
    consistency check fails or a window is lost, so acks from the
    abandoned stream can no longer steer the send cursor), and the
    adaptive window size between ``floor`` and ``ceiling``: an ack
    latency spiking well past the EWMA baseline (a congested or slow
    follower) halves the window toward the floor; acks near baseline
    grow it additively back toward the ceiling — AIMD, the classic
    shape for a windowed stream sharing a link. The baseline is an
    EWMA, not an all-time best: a persistent RTT shift (link weather, a
    follower moving racks) re-baselines within ~10 acks instead of
    reading as congestion forever.
    """

    __slots__ = ("window", "floor", "ceiling", "inflight_windows",
                 "inflight_entries", "epoch", "backoff", "ack_ewma_ms",
                 "floor_hits", "tasks")

    def __init__(self, ceiling: int) -> None:
        self.ceiling = max(1, ceiling)
        self.floor = max(1, self.ceiling // 8)
        self.window = self.ceiling  # start wide; congestion shrinks it
        self.inflight_windows = 0
        self.inflight_entries = 0
        self.epoch = 0
        self.backoff = False  # driver sleeps one beat before resuming
        self.ack_ewma_ms = 0.0
        #: times congestion drove the window down TO its floor — a
        #: cumulative counter because the pinned state itself is
        #: transient (AIMD regrows once the EWMA re-baselines) and a
        #: sampled gauge would miss it; the health plane's
        #: window-collapse detector judges deltas of this
        self.floor_hits = 0
        self.tasks: set[asyncio.Task] = set()

    def observe_ack(self, lat_ms: float) -> None:
        if self.ack_ewma_ms == 0.0:
            self.ack_ewma_ms = lat_ms
        if lat_ms > 4.0 * max(self.ack_ewma_ms, 0.1):
            shrunk = max(self.floor, self.window // 2)
            if shrunk <= self.floor and self.window > self.floor:
                self.floor_hits += 1
            self.window = shrunk
        elif self.window < self.ceiling:
            self.window = min(self.ceiling,
                              self.window + max(1, self.ceiling // 8))
        self.ack_ewma_ms += 0.1 * (lat_ms - self.ack_ewma_ms)


class RaftGroup:
    """One Raft group: per-group consensus + session + apply state.

    Shared services (transport, peer connections, knob-derived config,
    the storage object) are reached through ``self.server``; everything
    mutable per group lives on this object.
    """

    def __init__(self, server: Any, group_id: int,
                 state_machine: StateMachine, metrics: Any) -> None:
        self.server = server
        self.group_id = group_id
        self.address: Address = server.address
        self.members: list[Address] = list(server.boot_members)
        # wire tag: None on the single-group plane so every message is
        # byte-identical to the pre-refactor server; the group id otherwise
        self.wire_group: int | None = None if server.single else group_id
        self.name = (server.name if server.single
                     else f"{server.name}-g{group_id}")

        self.log = server.storage.build_log(
            name=f"{self.name}-{self.address.port}")
        self.term = 0
        self.voted_for: Address | None = None
        self.commit_index = 0
        self.last_applied = 0
        self.global_index = 0

        self.role = FOLLOWER
        self.leader_address: Address | None = None

        self.state_machine = state_machine
        self.executor = StateMachineExecutor(log=self.log)
        self.context = self.executor.context
        self.context.logger = logging.getLogger(
            f"{self.name}-{self.address.port}")
        state_machine.init(self.executor)

        self.sessions: dict[int, ServerSession] = {}
        self.context.sessions = self.sessions

        # leader volatile state
        self.next_index: dict[Address, int] = {}
        self.match_index: dict[Address, int] = {}
        self._last_quorum_contact: dict[Address, float] = {}
        self._replication_events: dict[Address, asyncio.Event] = {}
        self._replication_tasks: dict[Address, asyncio.Task] = {}
        self._peer_streams: dict[Address, _PeerStream] = {}
        self._expiring_sessions: set[int] = set()

        # apply-side bookkeeping
        self._commit_futures: dict[int, asyncio.Future] = {}
        self._event_pushes: set[asyncio.Task] = set()
        self._touched_sessions: set[ServerSession] = set()
        self._applied_event = asyncio.Event()
        self._publish_buffer: list | None = None
        self._window_pending_seqs: set[tuple[int, int]] = set()
        self._advance_scheduled = False  # single-member deferred commit
        # parallel-apply dependency tracking: resource keys / session
        # ids with vector rows staged (locally or in the server's fused
        # collector) whose device effects have not been dispatched yet;
        # _stage_rows counts them so the contiguous plane (which tracks
        # no keys) still bounds pending fused rows correctly
        self._stage_keys: set = set()
        self._stage_sessions: set[int] = set()
        self._stage_rows = 0

        self._election_timer: Scheduled | None = None
        self._leader_timer: Scheduled | None = None

        # read pump windows (per group: the gate is per-group leadership)
        self._read_windows: dict[str, list] = {}
        self._read_flush_scheduled = False

        # Edge read tier (docs/EDGE_READS.md): member-local subscriber
        # registry next to the event channels — resource id -> {session
        # id -> subscribed instance ids} plus the per-session reverse
        # map for death cleanup. NEVER replicated: only the member
        # holding a session's connection registers (it is the one that
        # can push), and a lost registry (failover, restart) degrades to
        # the client's staleness-gate re-seed, not to a wrong read.
        self._edge_subs: dict[int, dict[int, set[int]]] = {}
        self._edge_sessions: dict[int, set[int]] = {}
        self._edge_dirty: dict[int, int | None] = {}  # rid -> trace|None
        self._edge_flush_scheduled = False
        self._edge_pushes: set[asyncio.Task] = set()
        # delta-publication coalescing: a hot write stream batches this
        # long per flush, so fan-out cost is pushes-per-interval per
        # subscriber, not per commit (state-based merge makes the
        # coalescing free — subscribers converge on the latest state)
        self._edge_flush_s = max(
            0.0, knobs.get_float("COPYCAT_EDGE_FLUSH_MS")) / 1e3

        # Per-group metric objects on this group's registry (the SERVER
        # registry itself when single-group, so names/values are
        # bit-identical; a private registry merged under a group= label
        # into the stats surface otherwise).
        self.metrics = metrics
        m = metrics
        self._m_apply_entry = m.counter("applies_per_entry")
        self._m_append_entries = m.histogram("append_batch_entries")
        self._m_heartbeats = m.counter("append_heartbeats")
        self._m_vector_refused = m.counter("vector_classify_refused")
        self._m_single_lane = m.counter("commands_single_lane")
        self._m_fast_lane = m.counter("commands_fast_lane")
        self._m_general_lane = m.counter("commands_general_lane")
        self._m_keepalive_ms = m.histogram("keepalive_latency_ms")
        self._m_append_block = m.histogram("append_block_entries")
        self._m_vector_runs = m.counter("vector_runs")
        self._m_vector_ops = m.counter("vector_ops")
        self._m_run_length = m.histogram("apply_run_length")
        # Dependency-classified parallel apply (docs/SHARDING.md "Apply
        # ordering"): committed-window shape, runs spanning ineligible
        # entries, and conflict-forced flushes. Pre-created so the
        # family is present (count 0) in every snapshot the CI asserts.
        self._m_apply_window = m.histogram("apply.window_entries")
        self._m_apply_spans = m.counter("apply.parallel_spans")
        self._m_apply_conflicts = m.counter("apply.conflict_flushes")
        self._m_query_windows = m.counter("query_windows")
        self._m_query_ops = m.counter("query_ops")
        self._m_query_window_ops = m.histogram("query_window_ops")
        self._m_query_gate_saved = m.counter("query_gate_rounds_saved")
        self._m_query_device = m.counter("query_ops_device_lane")
        self._m_query_per_op = m.counter("query_ops_per_op_lane")
        self._m_query_level = {
            c.value: m.counter("query_reads", consistency=c.value)
            for c in QueryConsistency}
        self._m_repl_windows = m.counter("repl.windows_sent")
        self._m_repl_entries = m.counter("repl.entries_sent")
        self._m_repl_window_entries = m.histogram("repl.window_entries")
        self._m_repl_ack_ms = m.histogram("repl.ack_ms")
        self._m_repl_rewinds = m.counter("repl.rewinds")
        self._m_repl_stalls = m.counter("repl.stalls")
        self._m_repl_backpressure = m.counter("repl.backpressure_waits")
        self._m_repl_inflight_windows = m.gauge("repl.windows_inflight")
        self._m_repl_inflight_entries = m.gauge("repl.entries_inflight")
        self._m_snap_taken = m.counter("snap.snapshots_taken")
        self._m_snap_bytes = m.counter("snap.snapshot_bytes")
        self._m_snap_ms = m.histogram("snap.snapshot_ms")
        self._m_snap_trunc = m.counter("snap.truncated_entries")
        self._m_snap_chunks_sent = m.counter("snap.install_chunks_sent")
        self._m_snap_chunks_recv = m.counter("snap.install_chunks_received")
        self._m_snap_installs_sent = m.counter("snap.installs_sent")
        self._m_snap_installs_recv = m.counter("snap.installs_received")
        self._m_snap_install_fail = m.counter("snap.install_failures")
        self._m_snap_restores = m.counter("snap.restores")
        self._m_snap_restore_ms = m.histogram("snap.restore_ms")
        self._m_snap_meta_fallback = m.counter("snap.meta_fallbacks")
        self._m_snap_capture_fail = m.counter("snap.capture_failures")
        # Edge read tier (docs/EDGE_READS.md): subscription registry +
        # delta publication accounting. Pre-created so the family is
        # present (count 0) in every snapshot the CI asserts.
        self._m_edge_subs = m.gauge("edge.subscriptions")
        self._m_edge_subscribes = m.counter("edge.subscribes")
        self._m_edge_unsubscribes = m.counter("edge.unsubscribes")
        self._m_edge_deltas = m.counter("edge.deltas_sent")
        self._m_edge_flushes = m.counter("edge.delta_flushes")
        self._m_edge_retired = m.counter("edge.entries_retired")
        # Per-phase commit-latency attribution (docs/OBSERVABILITY.md
        # "Cluster-wide causal tracing"): fed ONLY by traced requests —
        # the client's trace flag is the sampling switch, so the
        # untraced hot path never touches these. Pre-created so the
        # family is present (count 0) in every snapshot the CI asserts.
        self._m_lat_append = m.histogram("latency.append_ms")
        self._m_lat_quorum = m.histogram("latency.quorum_ms")
        self._m_lat_fsync = m.histogram("latency.fsync_ms")
        self._m_lat_apply = m.histogram("latency.apply_ms")
        self._m_lat_respond = m.histogram("latency.respond_ms")
        self._m_lat_commit = m.histogram("latency.commit_ms")
        self._m_lat_event_push = m.histogram("latency.event_push_ms")
        self._m_lat_follower = m.histogram("latency.follower_append_ms")

        # causal-tracing bookkeeping (all empty unless requests carry a
        # trace id — the disabled hot path pays empty-dict truthiness
        # checks only): watch = appended-index -> (trace, t_append) for
        # the quorum.wait split (popped the instant commit covers it);
        # window marks = appended-index -> trace for stamping
        # replication windows, retained until EVERY member has the
        # entry (pruned at global_index — a commit-time pop would stop
        # stamping windows to stragglers, losing exactly the laggy
        # members' spans); commit_t = trace -> instant the commit
        # boundary (incl. fsync) covered it, read by the awaiting
        # coroutine for the apply span; entry marks = log index ->
        # trace, consumed by the apply loop to stamp event pushes.
        self._trace_watch: dict[int, tuple[int, float]] = {}
        self._trace_window_marks: dict[int, int] = {}
        self._trace_commit_t: dict[int, float] = {}
        self._trace_entry_marks: dict[int, int] = {}
        self._member = str(self.address)
        self._trace_slow_ms = knobs.get_float("COPYCAT_TRACE_SLOW_MS")

        # health-plane fsync accounting (utils/health.py): cheap EWMA +
        # per-window max over the commit-boundary fsyncs, fed only when
        # the server's health plane is on (COPYCAT_HEALTH=0 keeps the
        # bare log.sync() calls — the A/B discipline)
        self._fsync_count = 0
        self._fsync_last_ms = 0.0
        self._fsync_ewma_ms = 0.0
        self._fsync_recent_max_ms = 0.0

        # crash-recovery plane (per group: own snapshot store + meta file)
        self._snapshots: SnapshotStore | None = None
        if self.storage.directory:
            self._snapshots = SnapshotStore(
                self.storage.directory, f"{self.name}-{self.address.port}")
        self._snap_index = 0
        self._snap_supported = True
        self._installing: dict | None = None
        self._install_term_cache: tuple[int, int] | None = None
        self._recovery_replay_s = 0.0
        self._recovery_boot_last = 0

        self._load_meta()
        self._boot_recover()
        self._recovery_boot_last = (
            self.log.last_index if self.log.last_index > self.last_applied
            else 0)

    # ------------------------------------------------------------------
    # shared-config delegation (live reads: tests flip these on the
    # server mid-run and the next operation must see the change)
    # ------------------------------------------------------------------

    @property
    def storage(self):
        return self.server.storage

    @property
    def election_timeout(self) -> float:
        return self.server.election_timeout

    @property
    def heartbeat_interval(self) -> float:
        return self.server.heartbeat_interval

    @property
    def session_timeout(self) -> float:
        return self.server.session_timeout

    @property
    def _closing(self) -> bool:
        return self.server._closing

    @property
    def _repl_pipeline(self) -> bool:
        return self.server._repl_pipeline

    @property
    def _repl_window(self) -> int:
        return self.server._repl_window

    @property
    def _repl_depth(self) -> int:
        return self.server._repl_depth

    @property
    def _repl_max_inflight(self) -> int:
        return self.server._repl_max_inflight

    @property
    def _strict_invariants(self) -> bool:
        return self.server._strict_invariants

    @property
    def _vector_pump(self) -> bool:
        return self.server._vector_pump

    @property
    def _read_pump(self) -> bool:
        return self.server._read_pump

    @property
    def _parallel_apply(self) -> bool:
        return self.server._parallel_apply

    @property
    def _apply_fuse(self) -> bool:
        return self.server._apply_fuse

    @property
    def _snap_enabled(self) -> bool:
        return self.server._snap_enabled

    @property
    def _snap_every(self) -> int:
        return self.server._snap_every

    @property
    def _snap_retain(self) -> int:
        return self.server._snap_retain

    @property
    def _snap_chunk(self) -> int:
        return self.server._snap_chunk

    @property
    def _fsync_on_commit(self) -> bool:
        return self.server._fsync_on_commit

    @property
    def _snap_serializer(self) -> Serializer:
        return self.server._snap_serializer

    async def _peer_connection(self, peer: Address) -> Connection | None:
        return await self.server._peer_connection(peer)

    # ------------------------------------------------------------------
    # lifecycle (driven by the server's open/close)
    # ------------------------------------------------------------------

    def start(self) -> None:
        self._become_follower(self.term, None, reset_timer=True)

    def shutdown(self) -> None:
        """Cancel timers/streams and fail everything pending (the group
        half of the server's ``_do_close``); the log closes here too."""
        self.server.drop_fused(self)
        self._cancel_timers()
        self._stop_replication()
        self._trace_clear()
        for task in list(self._edge_pushes):
            task.cancel()
        self._edge_pushes.clear()
        self._edge_subs.clear()
        self._edge_sessions.clear()
        self._edge_dirty.clear()
        for fut in self._commit_futures.values():
            if not fut.done():
                fut.set_exception(
                    msg.ProtocolError(msg.NO_LEADER, "server closed"))
        self._commit_futures.clear()
        for items in self._read_windows.values():
            for _, _, _, fut in items:
                if not fut.done():
                    fut.set_result((0, None, msg.NO_LEADER, "server closed"))
        self._read_windows.clear()
        self.log.close()

    def _cancel_timers(self) -> None:
        if self._election_timer is not None:
            self._election_timer.cancel()
            self._election_timer = None
        if self._leader_timer is not None:
            self._leader_timer.cancel()
            self._leader_timer = None

    # ------------------------------------------------------------------
    # persistence of (term, voted_for)
    # ------------------------------------------------------------------

    @property
    def _meta_path(self) -> str | None:
        if self.storage.directory:
            return os.path.join(
                self.storage.directory,
                f"{self.name}-{self.address.port}.meta")
        return None

    def _persist_meta(self) -> None:
        # tmp + fsync + atomic rename: a torn (term, voted_for) write is a
        # Raft SAFETY hazard — a lost vote record lets this server vote
        # twice in the same term after a restart, electing two leaders.
        path = self._meta_path
        if path:
            write_atomic(path, json.dumps(
                {"term": self.term,
                 "voted_for": str(self.voted_for) if self.voted_for else None}
            ).encode())

    def _load_meta(self) -> None:
        path = self._meta_path
        if not path or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                meta = json.load(f)
            self.term = int(meta.get("term", 0))
            voted = meta.get("voted_for")
            self.voted_for = Address.parse(voted) if voted else None
        except (json.JSONDecodeError, ValueError, KeyError, OSError) as e:
            # A corrupt/truncated meta file (a torn write from a pre-atomic
            # version, or disk damage) must not kill the boot: fall back to
            # zero-state — conservative for elections (this server may
            # re-vote in a term it already voted in, which the atomic
            # writer above makes vanishingly unlikely to matter) — and
            # leave a loud trail: log, counter, and a flight-recorder note
            # when the device telemetry hub is reachable.
            logger.warning("%s meta file %s corrupt (%s); booting with "
                           "zero vote state", self.name, path, e)
            self._m_snap_meta_fallback.inc()
            self._flight_note("meta_corrupt", path=path, error=str(e))
            self.term = 0
            self.voted_for = None

    def _trace_span(self, trace: int, name: str, t0: float, t1: float,
                    hist=None, **meta: Any) -> None:
        """Record one server-side span under ``trace`` — tagged with
        this member + group so the cross-member assembly can attribute
        it — and feed the matching ``latency.*`` phase histogram."""
        TRACER.span(trace, name, t0, t1, member=self._member,
                    group=self.group_id, **meta)
        if hist is not None:
            hist.record((t1 - t0) * 1e3)

    def _trace_note_slow(self, trace: int, t0: float, t1: float) -> None:
        """Slow-trace exemplar: a traced request whose server residency
        exceeded ``COPYCAT_TRACE_SLOW_MS`` lands in the device-plane
        flight recorder, next to whatever fault caused it."""
        ms = (t1 - t0) * 1e3
        if ms >= self._trace_slow_ms:
            self._flight_note("slow_trace", trace=trace,
                              ms=round(ms, 3))

    def _trace_clear(self) -> None:
        """Drop causal-tracing bookkeeping (leadership loss/shutdown:
        the awaiting coroutines are failing with NOT_LEADER and nothing
        will consume the watches)."""
        self._trace_watch.clear()
        self._trace_window_marks.clear()
        self._trace_commit_t.clear()
        self._trace_entry_marks.clear()

    def _flight_note(self, kind: str, **fields) -> None:
        """Best-effort note in the device-plane flight recorder (the ring
        ``testing/nemesis.py`` faults also land in), so a recovery anomaly
        sits next to whatever fault caused it in one /flight dump. With
        the health plane on, the note also lands in the durable black-box
        so it survives a crash — all via the server's ``health_note``
        (one implementation of the hub-else-blackbox + spill wiring)."""
        self.server.health_note(
            kind, group=None if self.server.single else self.group_id,
            **fields)

    def _note_fsync(self, ms: float) -> None:
        """Health-plane fsync accounting: last/max/EWMA of the
        commit-boundary fsync latency (the fsync-spike detector's
        input; ``fsync_recent_max`` is consumed by ``health_sample``)."""
        self._fsync_count += 1
        self._fsync_last_ms = ms
        if ms > self._fsync_recent_max_ms:
            self._fsync_recent_max_ms = ms
        self._fsync_ewma_ms = (
            ms if self._fsync_ewma_ms == 0.0
            else self._fsync_ewma_ms + 0.1 * (ms - self._fsync_ewma_ms))

    def _sync_log(self) -> None:
        """Commit-boundary ``log.sync()`` with health-plane latency
        accounting; COPYCAT_HEALTH=0 keeps the bare sync (not even the
        clock reads) — the A/B lane."""
        if not self.server._health_enabled:
            self.log.sync()
            return
        t0 = time.perf_counter()
        self.log.sync()
        self._note_fsync((time.perf_counter() - t0) * 1e3)

    # ------------------------------------------------------------------
    # snapshot capture / restore (crash-recovery plane)
    # ------------------------------------------------------------------

    def _wire_session(self, session: ServerSession) -> None:
        """Route the session's publish through touched-session tracking /
        the windowed-apply publish buffer (installed at register-apply
        time AND at snapshot restore — restored sessions must publish
        exactly like never-crashed ones)."""
        original_publish = session.publish

        def tracked_publish(event: str, message: Any = None,
                            _orig=original_publish, _s=session) -> None:
            buf = self._publish_buffer
            if buf is not None:
                # windowed apply: buffered, replayed in log order at the
                # entry's finalization (chains complete out of order)
                buf.append((_orig, event, message, _s))
            else:
                _orig(event, message)
                self._session_touched(_s)

        session.publish = tracked_publish  # type: ignore[method-assign]

    def _snapshot_payload(self) -> bytes | None:
        """Serialize the full replicated image at ``last_applied``, or
        ``None`` when the state machine opts out of snapshotting."""
        machine_state = self.state_machine.snapshot_state()
        if machine_state is NotImplemented:
            if self._snap_supported:
                self._snap_supported = False
                logger.info(
                    "%s state machine %s does not support snapshots; "
                    "staying on the replay-only recovery path", self.name,
                    type(self.state_machine).__name__)
            return None
        payload = {
            "version": 1,
            "index": self.last_applied,
            "term": self.log.term_at(self.last_applied) or self.term,
            "clock": self.context.clock,
            "members": [str(m) for m in self.members],
            "sessions": [s.snapshot_dict() for s in self.sessions.values()],
            "machine": machine_state,
        }
        return self._snap_serializer.write(payload)

    def _take_snapshot(self) -> bool:
        """Capture + persist one snapshot at ``last_applied``, then release
        the log prefix behind it (keeping ``COPYCAT_SNAPSHOT_RETAIN``
        entries so slightly-lagging followers avoid an install)."""
        index = self.last_applied
        t0 = time.perf_counter()
        try:
            data = self._snapshot_payload()
            if data is None:
                return False
            self._snapshots.save(index, data)
            self._snapshots.gc(keep=2)
            self._snap_index = index
            self._m_snap_taken.inc()
            self._m_snap_bytes.inc(len(data))
            self._m_snap_ms.record((time.perf_counter() - t0) * 1e3)
            released = self.log.truncate_prefix(index - self._snap_retain)
            self._m_snap_trunc.inc(released)
        except Exception:  # noqa: BLE001 - capture must never kill apply
            # serialization bugs AND storage I/O (disk full, EIO on the
            # tmp write/rename, segment deletion): the apply/commit path
            # that called us must keep running either way
            logger.exception("%s snapshot capture at %d failed", self.name,
                             index)
            self._m_snap_capture_fail.inc()
            self._flight_note("snapshot_failed", index=index)
            return False
        logger.debug("%s snapshot at %d (%d bytes, %d entries released)",
                     self.name, index, len(data), released)
        return True

    def _maybe_snapshot(self) -> None:
        if (self._snap_enabled and self._snap_supported
                and self._snapshots is not None
                and self.last_applied - self._snap_index >= self._snap_every):
            # staged-but-undispatched fused vector rows are device
            # effects the image at last_applied must include — drain
            # the collector before capturing (a no-op when empty)
            self.server.flush_fused()
            self._take_snapshot()

    def _boot_recover(self) -> None:
        """Load the newest valid snapshot and restore state at boot, so the
        log tail — not the whole history — is all that replays (recovery
        time bounded by the snapshot cadence).  With COPYCAT_SNAPSHOTS=0
        this is a no-op: the replay-only path, bit-identically."""
        if not self._snap_enabled or self._snapshots is None:
            return
        snap = self._snapshots.newest()
        if snap is None:
            return
        index, data = snap
        try:
            payload = self._snap_serializer.read(data)
            self._restore_snapshot(payload)
        except Exception:  # noqa: BLE001 - fall back to full replay
            logger.exception("%s snapshot restore at %d failed; falling "
                             "back to full replay", self.name, index)
            self._flight_note("snapshot_restore_failed", index=index)
            # scrub anything a partial restore touched before replaying
            # from zero — replaying onto half-restored sessions/clock
            # would silently diverge this member (the machine hooks are
            # ordered to mutate last, see _restore_snapshot)
            self.sessions.clear()
            self.context.clock = 0.0
            self.last_applied = 0
            self.commit_index = 0
            self._snap_index = 0

    def _restore_snapshot(self, payload: dict) -> None:
        """Install one decoded snapshot image (boot recovery and the
        follower side of install streaming share this path)."""
        t0 = time.perf_counter()
        index = payload["index"]
        term = payload["term"]
        # vector rows parked in the server's fused collector belong to
        # entries the image (index > last_applied) already covers —
        # dispatch them against the PRE-restore state they were staged
        # on, or they would double-apply on top of the restored image
        # at the end-of-turn tick (a no-op at boot / when empty)
        self.server.flush_fused()
        # decode EVERYTHING decodable into locals before the first
        # mutation of self, so a malformed image fails fast with this
        # server still pristine (the boot path then falls back to full
        # replay cleanly; the install path refuses the chunk)
        members = [Address.parse(m) for m in payload["members"]]
        restored = [ServerSession.from_snapshot(s)
                    for s in payload["sessions"]]
        self.context.clock = payload["clock"]
        if members:
            self.members = members
        # session plane: replicated halves restored, publish re-wired; the
        # dict object is shared with context.sessions — mutate in place
        self.sessions.clear()
        for session in restored:
            self._wire_session(session)
            self.sessions[session.id] = session
        self.state_machine.restore_state(payload["machine"], self.sessions)
        # log alignment: keep a matching tail, otherwise restart past the
        # snapshot boundary (Raft snapshot-install rule)
        log = self.log
        if log.last_index > index and log.term_at(index) in (0, term) \
                and log.first_index <= index + 1:
            if log.prefix_index < index - self._snap_retain:
                self._m_snap_trunc.inc(
                    log.truncate_prefix(index - self._snap_retain))
        elif log.last_index != index or log.term_at(index) not in (0, term) \
                or log.first_index > index + 1:
            log.reset_to(index, term)
        self.last_applied = index
        self.commit_index = max(self.commit_index, index)
        self._snap_index = index
        self._m_snap_restores.inc()
        self._m_snap_restore_ms.record((time.perf_counter() - t0) * 1e3)
        self._applied_event.set()

    # ------------------------------------------------------------------
    # membership views
    # ------------------------------------------------------------------

    @property
    def peers(self) -> list[Address]:
        return [m for m in self.members if m != self.address]

    @property
    def quorum(self) -> int:
        return len(self.members) // 2 + 1

    # ------------------------------------------------------------------
    # role transitions
    # ------------------------------------------------------------------

    def _become_follower(self, term: int, leader: Address | None,
                         reset_timer: bool = True) -> None:
        if term > self.term:
            self.term = term
            self.voted_for = None
            self._persist_meta()
        was_leader = self.role == LEADER
        self.role = FOLLOWER
        if leader is not None:
            self.leader_address = leader
        if was_leader:
            self._stop_replication()
            self._fail_pending(msg.NOT_LEADER)
            self._expiring_sessions.clear()
        if reset_timer:
            self._reset_election_timer()

    def _reset_election_timer(self) -> None:
        if self._election_timer is not None:
            self._election_timer.cancel()
        base = self.election_timeout
        if self.server.single:
            timeout = random.uniform(base, base * 2)
        else:
            # Leadership spread (docs/SHARDING.md): the member at this
            # group's deterministic preference rank fires FIRST — rank 0
            # (member ``g % N`` over the sorted member list) draws from
            # [0.6T, T), strictly below everyone else's [T, 2T), so at
            # boot every member wins ~G/N groups without coordination.
            # Higher ranks add a per-rank offset, so on leader loss the
            # next LIVE rank tends to win (rebalance-on-timeout). Ranks
            # are unique per group — no two members share a band, which
            # keeps split votes as unlikely as the legacy distribution.
            ranked = sorted(self.members, key=lambda a: (a.host, a.port))
            n = len(ranked)
            try:
                rank = (ranked.index(self.address)
                        - self.group_id) % n
            except ValueError:  # joining: not in members yet
                rank = n
            if rank == 0:
                timeout = random.uniform(base * 0.6, base)
            else:
                timeout = (random.uniform(base, base * 2)
                           + base * 0.3 * rank)
        self._election_timer = Scheduled(timeout, None, self._start_election)

    async def _start_election(self) -> None:
        if self._closing or self.role == LEADER:
            return
        self.role = CANDIDATE
        self.term += 1
        self.voted_for = self.address
        self.leader_address = None
        self._persist_meta()
        term = self.term
        self.metrics.counter("raft_elections_started").inc()
        logger.debug("%s starting election for term %d", self.name, term)
        self._reset_election_timer()  # re-elect if this round stalls

        votes = 1  # self
        if votes >= self.quorum:
            self._become_leader()
            return

        async def request_vote(peer: Address) -> bool:
            conn = await self._peer_connection(peer)
            if conn is None:
                return False
            try:
                response = await asyncio.wait_for(
                    conn.send(msg.VoteRequest(
                        term=term, candidate=self.address,
                        last_log_index=self.log.last_index,
                        last_log_term=self.log.term_at(self.log.last_index),
                        group=self.wire_group)),
                    self.election_timeout)
            except (TransportError, OSError, asyncio.TimeoutError):
                return False
            if response.term is not None and response.term > self.term:
                self._become_follower(response.term, None)
                return False
            return bool(response.voted) and response.term == term

        tasks = [spawn(request_vote(p), name="request-vote")
                 for p in self.peers]
        for fut in asyncio.as_completed(tasks):
            granted = await fut
            if self.role != CANDIDATE or self.term != term:
                break
            if granted:
                votes += 1
                if votes >= self.quorum:
                    self._become_leader()
                    break
        for t in tasks:
            if not t.done():
                t.cancel()

    def _become_leader(self) -> None:
        if self.role == LEADER:
            return
        self.role = LEADER
        self.leader_address = self.address
        self.metrics.counter("raft_leader_transitions").inc()
        logger.info("%s elected leader for term %d", self.name, self.term)
        if self._election_timer is not None:
            self._election_timer.cancel()
            self._election_timer = None
        for peer in self.peers:
            self.next_index[peer] = self.log.last_index + 1
            self.match_index[peer] = 0
            self._replication_events[peer] = asyncio.Event()
            self._replication_tasks[peer] = spawn(
                self._replicate_loop(peer), name=f"replicate-{peer}")
        self._last_quorum_contact = {self.address: time.monotonic()}
        # Reset every open session's contact clock: last_contact is
        # LEADER-LOCAL wall time (replicated keep-alives advance only the
        # deterministic log clock), so a re-elected leader would otherwise
        # judge staleness from its PREVIOUS term's contacts and expire
        # sessions that kept keep-aliving the interim leader all along —
        # found by the partition+loss soak (tests/test_nemesis_raft.py).
        # Every session gets one full timeout from takeover, the
        # reference's new-leader grace.
        now = time.monotonic()
        for session in self.sessions.values():
            session.last_contact = now
        # Commit an entry from this term immediately (Raft §5.4.2) and advance
        # the state machine clock.
        self._append(NoOpEntry())
        self._leader_timer = Scheduled(self.heartbeat_interval,
                                       self.heartbeat_interval,
                                       self._leader_maintenance)

    def _stop_replication(self) -> None:
        for task in self._replication_tasks.values():
            task.cancel()
        self._replication_tasks.clear()
        self._replication_events.clear()
        # drain the pipelined lanes: in-flight window sends die with the
        # stream (their ack handling is role-gated anyway)
        for ps in self._peer_streams.values():
            for task in list(ps.tasks):
                task.cancel()
        self._peer_streams.clear()
        self._refresh_repl_gauges()
        if self._leader_timer is not None:
            self._leader_timer.cancel()
            self._leader_timer = None

    def _fail_pending(self, code: str) -> None:
        self._trace_clear()
        for fut in self._commit_futures.values():
            if not fut.done():
                fut.set_exception(
                    msg.ProtocolError(code, leader=self.leader_address))
        self._commit_futures.clear()
        for session in self.sessions.values():
            for fut in session.command_futures.values():
                if not fut.done():
                    fut.set_exception(
                        msg.ProtocolError(code, leader=self.leader_address))
            session.command_futures.clear()
            session.pending_ops.clear()
            session.next_append_seq = 0  # re-derive on next leadership

    # ------------------------------------------------------------------
    # leader: append + replication + commit advance
    # ------------------------------------------------------------------

    def _append(self, entry: Entry) -> int:
        entry.term = self.term
        entry.timestamp = time.time()
        index = self.log.append(entry)
        self._signal_replication()
        if len(self.members) == 1:
            # Defer commit advance to the end of the current event-loop
            # turn so a burst of concurrent appends commits and APPLIES as
            # one batch (the device window amortizes engine rounds across
            # the whole batch; multi-member clusters batch naturally via
            # replication acks).
            if not self._advance_scheduled:
                self._advance_scheduled = True
                asyncio.get_running_loop().call_soon(self._advance_deferred)
        return index

    def _advance_deferred(self) -> None:
        self._advance_scheduled = False
        if self.role == LEADER and not self._closing:
            self._advance_commit()

    def _signal_replication(self) -> None:
        for event in self._replication_events.values():
            event.set()

    async def _append_and_wait(self, entry: Entry) -> Any:
        """Append an entry and wait until it is committed and applied."""
        # Register the future before appending: on a single-member cluster
        # the append commits and applies within the same event-loop turn.
        index = self.log.last_index + 1
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._commit_futures[index] = fut
        actual = self._append(entry)
        assert actual == index
        return await fut

    async def _replicate_loop(self, peer: Address) -> None:
        try:
            if self._repl_pipeline:
                await self._replicate_pipelined(peer)
            else:
                await self._replicate_stop_and_wait(peer)
        except asyncio.CancelledError:
            pass
        except Exception:
            logger.exception("replication loop to %s failed", peer)

    # -- stop-and-wait lane (COPYCAT_REPL_PIPELINE=0): one window in
    # -- flight per peer, the pre-pipeline behavior bit-identically —
    # -- the cluster bench's A/B baseline
    async def _replicate_stop_and_wait(self, peer: Address) -> None:
        event = self._replication_events[peer]
        while self.role == LEADER and not self._closing:
            event.clear()
            await self._replicate_once(peer)
            if self.role != LEADER:
                return
            if self.next_index.get(peer, 1) > self.log.last_index:
                try:
                    await asyncio.wait_for(event.wait(),
                                           self.heartbeat_interval)
                except asyncio.TimeoutError:
                    pass

    def _stage_window(self, next_index: int,
                      limit: int) -> tuple[msg.AppendRequest, int, int]:
        """Build one append window [next_index, covered_end] — shared by
        both lanes so their wire shape can never drift apart. The end of
        the covered index range may omit compacted (cleaned) entries:
        they are only ever compacted once replicated to ALL members, so
        the follower already has them (it gap-fills via ``fill_to``)."""
        prev_index = next_index - 1
        entries = self.log.entries_from(next_index, limit=limit)
        covered_end = min(next_index + limit - 1, self.log.last_index)
        trace = None
        if self._trace_window_marks and covered_end >= next_index:
            # this window carries a traced entry toward quorum: stamp
            # ``(trace id, entry index)`` (an OPTIONAL trailing wire
            # field — untraced windows stay byte-identical) so the
            # follower records its ingest under the same causal
            # timeline AND marks the entry for event-push attribution
            # (the connection-holding member pushes from its own apply).
            # Window marks outlive the quorum watch: a straggler whose
            # window is staged after commit still gets the stamp. The
            # field carries ONE (trace, index) pair — when entries of
            # several concurrent traces coalesce into one window, only
            # the first gets follower-side spans (a documented sampling
            # limitation, not a correctness hazard: leader-side phases
            # and the client span always land for every trace).
            trace = next(((t, i) for i, t
                          in self._trace_window_marks.items()
                          if next_index <= i <= covered_end), None)
        request = msg.AppendRequest(
            term=self.term, leader=self.address,
            prev_index=prev_index, prev_term=self.log.term_at(prev_index),
            entries=entries, commit_index=self.commit_index,
            global_index=self.global_index,
            fill_to=covered_end if covered_end >= next_index else None,
            group=self.wire_group, trace=trace)
        if covered_end >= next_index:
            self._m_repl_windows.inc()
            self._m_repl_entries.inc(len(entries))
            self._m_repl_window_entries.record(len(entries))
        return request, prev_index, covered_end

    async def _replicate_once(self, peer: Address) -> None:
        conn = await self._peer_connection(peer)
        if conn is None:
            await asyncio.sleep(self.heartbeat_interval)
            return
        next_index = self.next_index.get(peer, self.log.last_index + 1)
        if next_index <= self.log.prefix_index:
            # the entries this follower needs were released behind a
            # snapshot: stream the snapshot, then resume appending
            await self._install_to_peer(peer, conn)
            return
        request, prev_index, covered_end = self._stage_window(
            next_index, self._repl_window)
        t0 = time.perf_counter()
        try:
            response = await asyncio.wait_for(conn.send(request),
                                              self.election_timeout)
        except (TransportError, OSError, asyncio.TimeoutError):
            self._m_repl_stalls.inc()
            await asyncio.sleep(self.heartbeat_interval)
            return
        if self.role != LEADER:
            return
        if response.term is not None and response.term > self.term:
            self._become_follower(response.term, None)
            return
        self._last_quorum_contact[peer] = time.monotonic()
        self._m_repl_ack_ms.record((time.perf_counter() - t0) * 1e3)
        if response.success:
            match = max(prev_index, covered_end)
            if match > self.match_index.get(peer, 0):
                self.match_index[peer] = match
            self.next_index[peer] = max(self.next_index.get(peer, 1),
                                        match + 1)
            self._advance_commit()
            if self.next_index[peer] <= self.log.last_index:
                self._replication_events[peer].set()  # keep streaming
        else:
            self._m_repl_rewinds.inc()
            hint = (response.last_index
                    if response.last_index is not None else prev_index - 1)
            new_next = max(1, min(prev_index, hint + 1))
            if new_next == next_index:
                # No rewind progress (e.g. follower in a weird state): back
                # off instead of hot-spinning the failure path.
                self._m_repl_stalls.inc()
                await asyncio.sleep(self.heartbeat_interval)
            self.next_index[peer] = new_next
            self._replication_events[peer].set()

    # -- pipelined lane (default): up to REPL_DEPTH windows in flight
    # -- per peer over the transport's correlated multiplexing; acks may
    # -- land out of order, match only moves forward, commit advances
    # -- per ack, a failed consistency check drains + rewinds the stream

    async def _replicate_pipelined(self, peer: Address) -> None:
        event = self._replication_events[peer]
        ps = _PeerStream(self._repl_window)
        self._peer_streams[peer] = ps
        try:
            while self.role == LEADER and not self._closing:
                conn = await self._peer_connection(peer)
                if conn is None:
                    await asyncio.sleep(self.heartbeat_interval)
                    continue
                if ps.backoff:
                    # a lost window or a no-progress rewind: wait one beat
                    # instead of hot-spinning the failure path
                    ps.backoff = False
                    await asyncio.sleep(self.heartbeat_interval)
                    continue
                if self.next_index.get(peer, 1) <= self.log.prefix_index:
                    # follower fell behind the prefix-truncated log: the
                    # append stream cannot serve it — drain in-flight
                    # windows, then stream the snapshot through the same
                    # connection (chunks ride the correlated multiplexing
                    # with the stream's depth + AIMD accounting), and
                    # resume appending where the snapshot ends
                    if ps.inflight_windows:
                        try:
                            await asyncio.wait_for(event.wait(),
                                                   self.heartbeat_interval)
                        except asyncio.TimeoutError:
                            pass
                        continue
                    await self._install_to_peer(peer, conn, ps)
                    continue
                event.clear()
                sent = self._pump_windows(peer, ps, conn)
                if (not sent and not ps.inflight_windows
                        and self.next_index.get(peer, 1)
                        > self.log.last_index):
                    # idle stream: heartbeat cadence keeps the follower's
                    # election timer reset and the leader lease fresh
                    try:
                        await asyncio.wait_for(event.wait(),
                                               self.heartbeat_interval)
                    except asyncio.TimeoutError:
                        self._spawn_window(peer, ps, conn)
                    continue
                # streaming or backpressured: wake on the next ack (the
                # send task sets the event) or new appends
                try:
                    await asyncio.wait_for(event.wait(),
                                           self.heartbeat_interval)
                except asyncio.TimeoutError:
                    pass
        finally:
            self._peer_streams.pop(peer, None)
            for task in list(ps.tasks):
                task.cancel()

    def _pump_windows(self, peer: Address, ps: _PeerStream,
                      conn: Connection) -> bool:
        """Launch append windows until the stream is caught up or the
        in-flight caps (windows, entries) push back; True if any window
        was sent this pump."""
        sent = False
        while (self.role == LEADER and not self._closing
               and ps.inflight_windows < self._repl_depth
               and ps.inflight_entries < self._repl_max_inflight
               and self.next_index.get(peer, 1) <= self.log.last_index):
            self._spawn_window(peer, ps, conn)
            sent = True
        if (self.next_index.get(peer, 1) <= self.log.last_index
                and (ps.inflight_windows >= self._repl_depth
                     or ps.inflight_entries >= self._repl_max_inflight)):
            # entries are waiting but the caps hold them back: a slow
            # follower cannot pin unbounded log memory — count the wait
            self._m_repl_backpressure.inc()
        return sent

    def _spawn_window(self, peer: Address, ps: _PeerStream,
                      conn: Connection) -> None:
        """Stage one append window [next_index, covered_end] and send it
        without awaiting the ack (the ack lands in ``_send_window``).
        The send cursor advances optimistically; a failed consistency
        check or lost window rewinds it (epoch-gated)."""
        next_index = self.next_index.get(peer, self.log.last_index + 1)
        # clamp to the remaining in-flight entry budget so the gauge's
        # documented bound (peers x COPYCAT_REPL_MAX_INFLIGHT) is exact —
        # without it the last window could overshoot by window-1 entries
        limit = min(ps.window,
                    max(1, self._repl_max_inflight - ps.inflight_entries))
        request, prev_index, covered_end = self._stage_window(
            next_index, limit)
        if covered_end >= next_index:
            self.next_index[peer] = covered_end + 1  # optimistic cursor
        ps.inflight_windows += 1
        ps.inflight_entries += max(0, covered_end - prev_index)
        self._refresh_repl_gauges()
        task = spawn(
            self._send_window(peer, ps, conn, request, prev_index,
                              covered_end, ps.epoch, time.perf_counter()),
            name="repl-window")
        ps.tasks.add(task)
        task.add_done_callback(ps.tasks.discard)

    async def _send_window(self, peer: Address, ps: _PeerStream,
                           conn: Connection, request: msg.AppendRequest,
                           prev_index: int, covered_end: int, epoch: int,
                           t0: float) -> None:
        try:
            response = await asyncio.wait_for(conn.send(request),
                                              self.election_timeout)
        except (TransportError, OSError, asyncio.TimeoutError):
            response = None
        finally:
            ps.inflight_windows -= 1
            ps.inflight_entries -= max(0, covered_end - prev_index)
            self._refresh_repl_gauges()
        event = self._replication_events.get(peer)
        try:
            if self._closing or self.role != LEADER:
                return
            if response is None:
                # lost window (dead/slow link): rewind the send cursor to
                # resend from this window's start once the link recovers;
                # acks of the abandoned stream no longer steer the cursor
                if epoch == ps.epoch:
                    ps.epoch += 1
                    ps.backoff = True
                    self._m_repl_stalls.inc()
                    self.next_index[peer] = min(
                        self.next_index.get(peer, 1), prev_index + 1)
                return
            if response.term is not None and response.term > self.term:
                self._become_follower(response.term, None)
                return
            self._last_quorum_contact[peer] = time.monotonic()
            lat_ms = (time.perf_counter() - t0) * 1e3
            self._m_repl_ack_ms.record(lat_ms)
            ps.observe_ack(lat_ms)
            if response.success:
                # acks complete out of order: match only moves FORWARD
                match = max(prev_index, covered_end)
                if match > self.match_index.get(peer, 0):
                    self.match_index[peer] = match
                # a success ack is a safe resume point even from a stale
                # epoch (log matching held at the follower when it acked):
                # this heals the spurious rewind a reordered window causes
                if match + 1 > self.next_index.get(peer, 1):
                    self.next_index[peer] = match + 1
                self._advance_commit()
            else:
                if epoch != ps.epoch:
                    return  # the pipeline already rewound past this one
                ps.epoch += 1  # drain: stale in-flight acks are ignored
                self._m_repl_rewinds.inc()
                hint = (response.last_index
                        if response.last_index is not None
                        else prev_index - 1)
                new_next = max(1, min(prev_index, hint + 1))
                if new_next >= prev_index + 1:
                    # no rewind progress (log base reached and the
                    # follower still refuses): back off a beat
                    ps.backoff = True
                    self._m_repl_stalls.inc()
                self.next_index[peer] = new_next
        finally:
            if event is not None:
                event.set()  # wake the driver: pump more / resume rewind

    def _refresh_repl_gauges(self) -> None:
        self._m_repl_inflight_windows.set(
            sum(ps.inflight_windows for ps in self._peer_streams.values()))
        self._m_repl_inflight_entries.set(
            sum(ps.inflight_entries for ps in self._peer_streams.values()))

    # -- snapshot-install streaming (leader side) ----------------------

    async def _install_to_peer(self, peer: Address, conn: Connection,
                               ps: _PeerStream | None = None) -> bool:
        """Stream the newest snapshot to a follower whose ``next_index``
        fell behind the prefix-truncated log, then point the append
        stream just past the snapshot.  Chunks ride the connection's
        correlated multiplexing — up to the pipeline depth in flight
        (one at a time on the stop-and-wait lane) with each ack feeding
        the stream's AIMD/EWMA accounting.  Any failed or refused chunk
        aborts the attempt; the driver loop retries from scratch on its
        next beat (installs are rare and whole-retry keeps the follower
        assembly state trivial)."""
        snap = (self._snapshots.newest()
                if self._snap_enabled and self._snapshots is not None
                else None)
        if snap is None:
            # a prefix-truncated log with no readable snapshot cannot
            # serve this follower at all — operator-level damage
            logger.error("%s: follower %s needs entries <= %d but no "
                         "valid snapshot exists", self.name, peer,
                         self.log.prefix_index)
            self._m_snap_install_fail.inc()
            await asyncio.sleep(self.heartbeat_interval)
            return False
        index, payload = snap
        # boundary-term lookup without re-decoding the (possibly large)
        # payload on every attempt: cached per snapshot index
        cached = self._install_term_cache
        if cached is not None and cached[0] == index:
            snap_term = cached[1]
        else:
            try:
                snap_term = self._snap_serializer.read(payload)["term"]
            except Exception:  # noqa: BLE001 - corrupt-but-CRC-valid payload
                logger.exception("%s: snapshot %d undecodable", self.name,
                                 index)
                self._m_snap_install_fail.inc()
                await asyncio.sleep(self.heartbeat_interval)
                return False
            self._install_term_cache = (index, snap_term)
        term = self.term
        total = len(payload)
        chunk = self._snap_chunk
        sem = asyncio.Semaphore(self._repl_depth if ps is not None else 1)
        failed = False

        async def send_chunk(offset: int) -> None:
            nonlocal failed
            async with sem:
                if failed or self.role != LEADER or self._closing:
                    failed = True
                    return
                t0 = time.perf_counter()
                try:
                    response = await asyncio.wait_for(
                        conn.send(msg.InstallRequest(
                            term=term, leader=self.address, index=index,
                            snap_term=snap_term, total=total, offset=offset,
                            data=payload[offset:offset + chunk], done=False,
                            group=self.wire_group)),
                        self.election_timeout)
                except (TransportError, OSError, asyncio.TimeoutError):
                    failed = True
                    return
                if response.term is not None and response.term > self.term:
                    self._become_follower(response.term, None)
                    failed = True
                    return
                if not response.success:
                    failed = True
                    return
                self._m_snap_chunks_sent.inc()
                self._last_quorum_contact[peer] = time.monotonic()
                if ps is not None:
                    ps.observe_ack((time.perf_counter() - t0) * 1e3)

        await asyncio.gather(
            *(send_chunk(o) for o in range(0, total, chunk)))
        if not failed and self.role == LEADER and not self._closing:
            # final frame: the follower assembles, CRC-persists, restores
            try:
                response = await asyncio.wait_for(
                    conn.send(msg.InstallRequest(
                        term=term, leader=self.address, index=index,
                        snap_term=snap_term, total=total, offset=total,
                        data=b"", done=True, group=self.wire_group)),
                    self.election_timeout * 4)
            except (TransportError, OSError, asyncio.TimeoutError):
                failed = True
            else:
                if response.term is not None and response.term > self.term:
                    self._become_follower(response.term, None)
                    failed = True
                elif not response.success:
                    failed = True
        if failed or self.role != LEADER:
            self._m_snap_install_fail.inc()
            if ps is not None:
                ps.backoff = True
            else:
                await asyncio.sleep(self.heartbeat_interval)
            return False
        self._m_snap_installs_sent.inc()
        self._last_quorum_contact[peer] = time.monotonic()
        if index > self.match_index.get(peer, 0):
            self.match_index[peer] = index
        self.next_index[peer] = max(self.next_index.get(peer, 1), index + 1)
        logger.info("%s installed snapshot %d on %s (%d bytes)", self.name,
                    index, peer, total)
        self._advance_commit()
        return True

    def _advance_commit(self) -> None:
        if self.role != LEADER:
            return
        matches = sorted(
            [self.log.last_index]
            + [self.match_index.get(p, 0) for p in self.peers],
            reverse=True)
        candidate = matches[self.quorum - 1]
        if candidate > self.commit_index \
                and self.log.term_at(candidate) == self.term:
            if self._strict_invariants:
                # COPYCAT_INVARIANTS=strict: re-verify from first
                # principles that a REAL quorum matches the candidate —
                # the tripwire proving pipelined (out-of-order) acks can
                # never advance commit past actual replication. The raise
                # may land inside a spawned ack task (logged, not fatal),
                # so the violation ALSO counts on the registry — the
                # strict nemesis suite asserts the counter stayed 0.
                support = 1 + sum(1 for p in self.peers
                                  if self.match_index.get(p, 0) >= candidate)
                if support < self.quorum or candidate > self.log.last_index:
                    self.metrics.counter("repl.invariant_violations").inc()
                    logger.critical(
                        "commit invariant violated: candidate %d supported "
                        "by %d/%d (quorum %d, last %d)", candidate, support,
                        len(self.members), self.quorum, self.log.last_index)
                    raise AssertionError(
                        f"commit invariant violated: candidate {candidate} "
                        f"supported by {support}/{len(self.members)} "
                        f"(quorum {self.quorum}, last {self.log.last_index})")
            self.commit_index = candidate
            hit: list[int] = []
            if self._trace_watch:
                # traced entries the quorum just covered: close their
                # quorum.wait span here — the instant commit advanced —
                # and remember the commit instant so the awaiting
                # coroutine can attribute the apply phase separately
                now = time.perf_counter()
                for index in [i for i in self._trace_watch
                              if i <= candidate]:
                    trace, t_append = self._trace_watch.pop(index)
                    self._trace_span(trace, "quorum.wait", t_append, now,
                                     self._m_lat_quorum, index=index)
                    self._trace_commit_t[trace] = now
                    hit.append(trace)
            if self._fsync_on_commit:
                if hit:
                    t_s = time.perf_counter()
                    self.log.sync()
                    t_e = time.perf_counter()
                    if self.server._health_enabled:
                        self._note_fsync((t_e - t_s) * 1e3)
                    for trace in hit:
                        self._trace_span(trace, "group.fsync", t_s, t_e,
                                         self._m_lat_fsync)
                        self._trace_commit_t[trace] = t_e
                else:
                    self._sync_log()  # commit boundary: ack = durable
            self._apply_up_to(self.commit_index)
        # global index: minimum replicated position across all members
        if self.peers:
            self.global_index = min(
                [self.log.last_index]
                + [self.match_index.get(p, 0) for p in self.peers])
        else:
            self.global_index = self.last_applied
        if self._trace_window_marks:
            # every member holds entries <= global_index: no future
            # window will carry them, the stamps can go
            for i in [i for i in self._trace_window_marks
                      if i <= self.global_index]:
                del self._trace_window_marks[i]
        if self.log.cleaned_count > 0:
            self.log.compact(min(self.global_index, self.last_applied))

    # -- leader maintenance: clocks, session expiry --------------------

    def _leader_maintenance(self) -> None:
        if self.role != LEADER or self._closing:
            return
        now_wall = time.time()
        # Advance the deterministic clock when state-machine timers are due.
        deadline = self.executor.next_deadline()
        if deadline is not None and deadline <= now_wall:
            self._append(NoOpEntry())
        # Expire sessions that missed keep-alives (leader wall-clock
        # detector; expiry itself is replicated + deterministic via
        # UnregisterEntry). Each group judges its own replicas: keep-alives
        # fan out to every group, so contacts stay fresh cluster-wide for
        # a live client and every group expires within one timeout of a
        # dead one.
        now = time.monotonic()
        for session in list(self.sessions.values()):
            if session.state is not SessionState.OPEN \
                    or session.id in self._expiring_sessions:
                continue
            last = session.last_contact
            if last and now - last > session.timeout:
                self._expiring_sessions.add(session.id)
                self._append(UnregisterEntry(session_id=session.id,
                                             expired=True))

    def _lease_valid(self) -> bool:
        """True if a quorum acked within the last election timeout (read
        lease)."""
        if len(self.members) == 1:
            return True
        now = time.monotonic()
        fresh = 1 + sum(
            1 for p in self.peers
            if now - self._last_quorum_contact.get(p, 0.0)
            < self.election_timeout)
        return fresh >= self.quorum

    def _confirm_leadership_hook(self):
        """Single-group: route through the server attribute so tests and
        embedders patching ``server._confirm_leadership`` (the classic
        surface) still intercept the gate; the unpatched server delegates
        straight back here."""
        if self.server.single:
            return self.server._confirm_leadership()
        return self._confirm_leadership()

    async def _confirm_leadership(self) -> bool:
        """Full linearizability barrier: round-trip a heartbeat to a
        quorum."""
        if len(self.members) == 1:
            return True
        term = self.term

        async def ping(peer: Address) -> bool:
            conn = await self._peer_connection(peer)
            if conn is None:
                return False
            try:
                response = await asyncio.wait_for(
                    conn.send(msg.AppendRequest(
                        term=term, leader=self.address,
                        prev_index=self.log.last_index,
                        prev_term=self.log.term_at(self.log.last_index),
                        entries=[], commit_index=self.commit_index,
                        group=self.wire_group)),
                    self.election_timeout)
            except (TransportError, OSError, asyncio.TimeoutError):
                return False
            if response.term is not None and response.term > self.term:
                self._become_follower(response.term, None)
                return False
            if response.success:
                self._last_quorum_contact[peer] = time.monotonic()
            return bool(response.success)

        results = await asyncio.gather(*(ping(p) for p in self.peers))
        return (self.role == LEADER and self.term == term
                and 1 + sum(results) >= self.quorum)

    # ------------------------------------------------------------------
    # RPC handlers: raft (requests pre-routed to this group by the
    # server's dispatch on ``request.group``)
    # ------------------------------------------------------------------

    async def _on_vote(self, request: msg.VoteRequest) -> msg.VoteResponse:
        if request.term > self.term:
            self._become_follower(request.term, None)
        if request.term < self.term:
            return msg.VoteResponse(term=self.term, voted=False)
        up_to_date = (request.last_log_term, request.last_log_index) >= (
            self.log.term_at(self.log.last_index), self.log.last_index)
        if self.voted_for in (None, request.candidate) and up_to_date:
            self.voted_for = request.candidate
            self._persist_meta()
            self._reset_election_timer()
            return msg.VoteResponse(term=self.term, voted=True)
        return msg.VoteResponse(term=self.term, voted=False)

    async def _on_append(self, request: msg.AppendRequest
                         ) -> msg.AppendResponse:
        if request.term < self.term:
            # rejected before recording: appends from deposed leaders must
            # not pollute the append-size histogram / heartbeat counter
            return msg.AppendResponse(term=self.term, success=False,
                                      last_index=self.log.last_index)
        trace_mark = request.trace  # (trace id, traced entry index)
        if type(trace_mark) is not tuple or len(trace_mark) != 2:
            trace_mark = None  # malformed peer payload: ignore, don't die
        trace = trace_mark[0] if trace_mark is not None else None
        t_trace = time.perf_counter() if trace is not None else 0.0
        if request.entries:
            self._m_append_entries.record(len(request.entries))
        else:
            self._m_heartbeats.inc()
        if request.term > self.term or self.role != FOLLOWER:
            self._become_follower(request.term, request.leader)
        else:
            self.leader_address = request.leader
            self._reset_election_timer()

        prev_index = request.prev_index or 0
        if prev_index > 0:
            if prev_index > self.log.last_index:
                return msg.AppendResponse(term=self.term, success=False,
                                          last_index=self.log.last_index)
            local_term = self.log.term_at(prev_index)
            # A term of 0 on either side means "unknown" (slot compacted or
            # gap-filled cluster-wide) — log matching cannot check it;
            # accept.
            if local_term != 0 and (request.prev_term or 0) != 0 \
                    and local_term != request.prev_term \
                    and prev_index > self.last_applied:
                self.log.truncate(prev_index)
                return msg.AppendResponse(term=self.term, success=False,
                                          last_index=self.log.last_index)

        # Block ingest: one conflict scan over the window's prefix that
        # overlaps the local log (skip matches, truncate at the first
        # term conflict, fill compacted slots), then ONE
        # append_replicated_block for the entire new tail — instead of a
        # per-entry get/append_replicated walk (a pipelined leader
        # delivers windows of hundreds of entries back to back, and the
        # per-entry walk was the follower's hottest loop).
        entries = request.entries or []
        log = self.log
        append_from: int | None = None
        for k, entry in enumerate(entries):
            if entry.index > log.last_index:
                append_from = k
                break
            existing = log.get(entry.index)
            if existing is not None:
                if existing.term != entry.term:
                    log.truncate(entry.index)
                    append_from = k
                    break
            elif entry.index > self.last_applied:
                log.set_slot(entry)
        if append_from is not None:
            log.append_replicated_block(entries[append_from:])
            if self._fsync_on_commit:
                # the success ack below is what the leader counts toward
                # quorum commit: it must not rest on page-cache-only
                # bytes, or a cluster-wide power loss could erase an
                # acknowledged commit (a quorum of un-fsynced ackers
                # reboots without the entry and re-elects among
                # themselves) — sync BEFORE acking, per append window
                self._sync_log()

        fill_to = request.fill_to or 0
        if fill_to > self.log.last_index:
            self.log.fill_gap(fill_to)

        if trace is not None and trace_mark[1] > self.last_applied:
            # the window was ACCEPTED (every reject path returned above):
            # mark the traced entry so that, if this member holds the
            # client's connection, its apply attributes the event push —
            # marking before acceptance would let a rejected window's
            # stale mark mis-attribute a different entry later
            self._trace_entry_marks[trace_mark[1]] = trace

        commit = min(request.commit_index or 0, self.log.last_index)
        if commit > self.commit_index:
            self.commit_index = commit
            if self._fsync_on_commit:
                self._sync_log()  # commit boundary: acknowledged = durable
            self._apply_up_to(commit)
        global_index = getattr(request, "global_index", None)
        if global_index:
            self.log.compact(min(global_index, self.last_applied))
        if trace is not None:
            # the window carried a traced entry: this member's ingest
            # (conflict scan + block append + fsync + commit advance) on
            # the originating causal timeline
            self._trace_span(trace, "follower.append", t_trace,
                             time.perf_counter(), self._m_lat_follower,
                             n=len(entries))
        return msg.AppendResponse(term=self.term, success=True,
                                  last_index=self.log.last_index)

    async def _on_install(self, request: msg.InstallRequest
                          ) -> msg.InstallResponse:
        """Follower side of snapshot-install streaming: buffer chunks by
        offset, and on the final frame assemble, persist (atomic +
        CRC-framed, via the local snapshot store when one exists), restore
        the image, and restart the log just past it."""
        if request.term < self.term:
            return msg.InstallResponse(term=self.term, success=False)
        if not self._snap_enabled:
            # COPYCAT_SNAPSHOTS=0 pins this server to the replay-only
            # lane; a mixed-knob cluster surfaces loudly instead of
            # half-restoring
            return msg.InstallResponse(
                term=self.term, success=False, error=msg.INTERNAL,
                error_detail="snapshots disabled on this member")
        if request.term > self.term or self.role != FOLLOWER:
            self._become_follower(request.term, request.leader)
        else:
            self.leader_address = request.leader
            self._reset_election_timer()
        if request.index <= self.last_applied:
            # stale install (we caught up some other way): ack so the
            # leader's cursor advances past it
            return msg.InstallResponse(term=self.term, success=True,
                                       last_index=self.log.last_index)
        buf = self._installing
        if buf is None or buf["index"] != request.index:
            buf = self._installing = {"index": request.index,
                                      "term": request.snap_term,
                                      "total": request.total, "chunks": {}}
        if request.data:
            buf["chunks"][request.offset] = request.data
            self._m_snap_chunks_recv.inc()
        if not request.done:
            return msg.InstallResponse(term=self.term, success=True,
                                       offset=request.offset)
        # final frame: verify the byte range is contiguous and complete
        parts = sorted(buf["chunks"].items())
        pos = 0
        for offset, data in parts:
            if offset != pos:
                break
            pos = offset + len(data)
        if pos != buf["total"]:
            self._installing = None  # whole-retry contract (leader side)
            return msg.InstallResponse(term=self.term, success=False,
                                       offset=pos)
        payload_bytes = b"".join(data for _, data in parts)
        self._installing = None
        try:
            payload = self._snap_serializer.read(payload_bytes)
            if self._snapshots is not None:
                self._snapshots.save(request.index, payload_bytes)
                self._snapshots.gc(keep=2)
            self._restore_snapshot(payload)
        except Exception as e:  # noqa: BLE001 - refuse, don't die
            logger.exception("%s: snapshot install at %d failed",
                             self.name, request.index)
            self._flight_note("install_failed", index=request.index)
            self._m_snap_install_fail.inc()
            return msg.InstallResponse(term=self.term, success=False,
                                       error=msg.INTERNAL,
                                       error_detail=str(e))
        self._m_snap_installs_recv.inc()
        self._flight_note("snapshot_installed", index=request.index)
        logger.info("%s restored installed snapshot at %d", self.name,
                    request.index)
        return msg.InstallResponse(term=self.term, success=True,
                                   last_index=self.log.last_index)

    # ------------------------------------------------------------------
    # RPC handlers: session protocol (legacy single-group entry points —
    # the server delegates straight here when ``groups == 1``; the
    # multi-group ingress uses the *_local / command_block / serve_query
    # staging methods below instead)
    # ------------------------------------------------------------------

    def _not_leader(self, response_type: type) -> Any:
        return response_type(
            error=msg.NOT_LEADER if self.leader_address else msg.NO_LEADER,
            leader=self.leader_address)

    async def _on_register(self, connection: Connection,
                           request: msg.RegisterRequest
                           ) -> msg.RegisterResponse:
        if self.role != LEADER:
            response = self._not_leader(msg.RegisterResponse)
            response.members = self.members
            return response
        timeout = request.timeout or self.session_timeout
        try:
            index, sid, _ = await self._append_and_wait(
                RegisterEntry(client_id=request.client_id, timeout=timeout))
        except msg.ProtocolError as e:
            return msg.RegisterResponse(error=e.code, leader=e.leader,
                                        members=self.members)
        session = self.sessions.get(sid)
        if session is not None:
            session.connection = connection
            session.last_contact = time.monotonic()
        return msg.RegisterResponse(session_id=sid, timeout=timeout,
                                    members=self.members,
                                    groups=self.server.num_groups)

    async def _on_keepalive(self, connection: Connection,
                            request: msg.KeepAliveRequest
                            ) -> msg.KeepAliveResponse:
        if self.role != LEADER:
            response = self._not_leader(msg.KeepAliveResponse)
            response.members = self.members
            return response
        session = self.sessions.get(request.session_id)
        if session is None or session.state is not SessionState.OPEN:
            return msg.KeepAliveResponse(error=msg.UNKNOWN_SESSION,
                                         members=self.members)
        session.connection = connection
        session.last_contact = time.monotonic()
        if getattr(request, "unsubscribe", None):
            # member-local edge bookkeeping (docs/EDGE_READS.md): the
            # client's LRU evictions ride the keep-alive, never the log
            self.edge_unsubscribe(request.session_id, request.unsubscribe)
        t0 = time.perf_counter()
        try:
            await self._append_and_wait(KeepAliveEntry(
                session_id=request.session_id,
                command_seq=request.command_seq or 0,
                event_index=request.event_index or 0))
        except msg.ProtocolError as e:
            return msg.KeepAliveResponse(error=e.code, leader=e.leader,
                                         members=self.members)
        self._m_keepalive_ms.record((time.perf_counter() - t0) * 1e3)
        # Resend any event batches the client is missing.
        self._flush_events(session)
        return msg.KeepAliveResponse(members=self.members)

    async def _on_unregister(self, request: msg.UnregisterRequest
                             ) -> msg.UnregisterResponse:
        if self.role != LEADER:
            return self._not_leader(msg.UnregisterResponse)
        if request.session_id in self.sessions:
            try:
                await self._append_and_wait(
                    UnregisterEntry(session_id=request.session_id,
                                    expired=False))
            except msg.ProtocolError as e:
                return msg.UnregisterResponse(error=e.code, leader=e.leader)
        return msg.UnregisterResponse()

    async def _on_command(self, connection: Connection,
                          request: msg.CommandRequest) -> msg.CommandResponse:
        if self.role != LEADER:
            return self._not_leader(msg.CommandResponse)
        session = self.sessions.get(request.session_id)
        if session is None or session.state is not SessionState.OPEN:
            return msg.CommandResponse(error=msg.UNKNOWN_SESSION)
        session.connection = connection
        session.last_contact = time.monotonic()
        seq = request.seq
        self._m_single_lane.inc()
        trace = request.trace
        t0 = time.perf_counter() if trace is not None else 0.0

        staged, payload = self._stage_command(session, seq, request.operation)
        if staged == "done":
            index, result, error = payload
            if trace is not None:
                self._trace_span(trace, "group.cached", t0,
                                 time.perf_counter(), seq=seq)
            return self._command_response(session, index, result, error)
        if staged == "err":
            code, detail = payload
            return msg.CommandResponse(error=code, error_detail=detail)
        fut = payload
        if trace is not None:
            t1 = time.perf_counter()
            self._trace_span(trace, "group.append", t0, t1,
                             self._m_lat_append, seq=seq)
        try:
            index, result, error = await fut
        except msg.ProtocolError as e:
            return msg.CommandResponse(error=e.code, leader=e.leader)
        finally:
            if session.command_futures.get(seq) is fut:
                del session.command_futures[seq]
        if trace is not None:
            # coarse commit span (append -> commit+apply): the per-seq
            # lane stages through futures whose log index is unknown
            # here, so the quorum/apply split rides the block lanes
            self._trace_span(trace, "group.commit", t1,
                             time.perf_counter(), self._m_lat_commit,
                             index=index)
        return self._command_response(session, index, result, error)

    def _stage_command(self, session: ServerSession, seq: int,
                       operation: Any) -> tuple[str, Any]:
        """Dedup/enqueue one sequenced command; returns
        ``("done", (index, result, error))`` for a cache hit,
        ``("err", (code, detail))`` for a pruned duplicate, or
        ``("wait", future)`` once the command rides the log."""
        # Exactly-once: already applied -> cached response.
        cached = session.cached_response(seq)
        if cached is not None:
            return "done", cached
        if seq <= session.command_high:
            return "err", (msg.INTERNAL,
                           f"response for seq {seq} already pruned")
        # Already in flight (resubmission) -> share the future.
        fut = session.command_futures.get(seq)
        if fut is None:
            fut = asyncio.get_running_loop().create_future()
            session.command_futures[seq] = fut
            # Append in client seq order: concurrent submits can arrive
            # reordered (independent RPCs over reconnects); applying seq N
            # after N+1 would silently drop the write.
            if session.next_append_seq == 0:
                session.next_append_seq = session.command_high + 1
            if seq < session.next_append_seq:
                # already appended (a fast-lane block or earlier stage
                # still in flight): apply resolves the future from the
                # log; parking it in pending_ops would strand it there
                # forever (the drain walk never revisits passed seqs)
                # and re-appending would double-apply
                return "wait", fut
            session.pending_ops[seq] = operation
            while session.next_append_seq in session.pending_ops:
                next_seq = session.next_append_seq
                session.next_append_seq += 1
                self._append(CommandEntry(
                    session_id=session.id, seq=next_seq,
                    operation=session.pending_ops.pop(next_seq)))
        return "wait", fut

    async def _on_command_batch(self, connection: Connection,
                                request: msg.CommandBatchRequest
                                ) -> msg.CommandBatchResponse:
        """Micro-batched commands: stage EVERY entry first (one append
        burst → one apply window on the device executor), then await the
        outcomes in seq order. Per-entry results/errors travel in the
        response's ``entries``; session-fatal conditions ride the
        response-level error like the single-command path."""
        if self.role != LEADER:
            return self._not_leader(msg.CommandBatchResponse)
        session = self.sessions.get(request.session_id)
        if session is None or session.state is not SessionState.OPEN:
            return msg.CommandBatchResponse(error=msg.UNKNOWN_SESSION)
        session.connection = connection
        session.last_contact = time.monotonic()
        entries = request.entries or []
        trace = request.trace
        t0 = time.perf_counter() if trace is not None else 0.0
        # FAST LANE: a fresh contiguous seq run with nothing pending
        # stages as one append block behind ONE commit future — no
        # per-seq futures, no per-entry dedup dict walks; responses read
        # back from the session's (replicated) response cache. Anything
        # irregular — duplicates, seq gaps, ops already in flight — takes
        # the general per-entry staging below, which shares futures and
        # serves cached responses (exactly-once unchanged).
        n = len(entries)
        if (n and not session.pending_ops and not session.command_futures
                and entries[0][0] == session.command_high + 1
                and session.next_append_seq in (0, entries[0][0])
                # contiguity at C speed: a listcomp + range compare beats
                # the per-entry Python walk on 1k-op batches
                and [e[0] for e in entries]
                == list(range(entries[0][0], entries[0][0] + n))):
            self._m_fast_lane.inc(n)
            return await self._command_batch_fast(session, entries, trace, t0)
        self._m_general_lane.inc(n)
        staged = [(seq, *self._stage_command(session, seq, op))
                  for seq, op in entries]
        if trace is not None:
            t1 = time.perf_counter()
            self._trace_span(trace, "group.append", t0, t1,
                             self._m_lat_append, n=n)
        entries = []
        for seq, kind, payload in staged:
            if kind == "done":
                index, result, error = payload
                entries.append((seq, index, result,
                                msg.APPLICATION if error else None, error))
            elif kind == "err":
                code, detail = payload
                entries.append((seq, 0, None, code, detail))
            else:
                fut = payload
                try:
                    index, result, error = await fut
                    entries.append((seq, index, result,
                                    msg.APPLICATION if error else None,
                                    error))
                except msg.ProtocolError as e:
                    if e.code in (msg.NOT_LEADER, msg.NO_LEADER):
                        # promote routing failures to the RESPONSE level:
                        # the client's _request retry loop re-routes and
                        # resends the whole batch (seq dedup makes the
                        # resend exactly-once), matching the
                        # single-command path's transparent failover
                        return msg.CommandBatchResponse(
                            error=e.code, leader=e.leader,
                            error_detail=e.detail)
                    entries.append((seq, 0, None, e.code, e.detail))
                finally:
                    if session.command_futures.get(seq) is fut:
                        del session.command_futures[seq]
        if trace is not None:
            self._trace_span(trace, "group.commit", t1,
                             time.perf_counter(), self._m_lat_commit, n=n)
        return msg.CommandBatchResponse(event_index=session.event_index,
                                        entries=entries)

    async def _command_batch_fast(self, session: ServerSession,
                                  entries: list, trace: int | None = None,
                                  t0: float = 0.0
                                  ) -> msg.CommandBatchResponse:
        """Stage a fresh contiguous command run as one append block.

        Inlines ``_append``'s per-entry tail (term/timestamp stamp + log
        append) and pays replication signalling and the single-member
        deferred commit advance ONCE for the block. The await is a single
        commit future on the block's LAST index: every earlier entry
        applies first (in-order apply), so when it resolves the whole
        run's responses are in the session cache."""
        term = self.term
        sid = session.id
        now = time.time()
        index = self.log.append_block(
            [CommandEntry(term, now, sid, seq, op) for seq, op in entries])
        self._m_append_block.record(len(entries))
        session.next_append_seq = entries[0][0] + len(entries)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._commit_futures[index] = fut
        self._signal_replication()
        if len(self.members) == 1 and not self._advance_scheduled:
            self._advance_scheduled = True
            asyncio.get_running_loop().call_soon(self._advance_deferred)
        if trace is not None:
            t1 = time.perf_counter()
            self._trace_span(trace, "group.append", t0, t1,
                             self._m_lat_append, index=index,
                             n=len(entries))
            # quorum.wait / group.fsync close in _advance_commit the
            # instant the commit boundary covers this block; the apply
            # loop stamps event pushes via the per-index marks
            self._trace_watch[index] = (trace, t1)
            self._trace_window_marks[index] = trace
            for i in range(index - len(entries) + 1, index + 1):
                self._trace_entry_marks[i] = trace
        try:
            await fut
        except msg.ProtocolError as e:
            if trace is not None:
                self._trace_watch.pop(index, None)
                self._trace_commit_t.pop(trace, None)
                for i in range(index - len(entries) + 1, index + 1):
                    self._trace_entry_marks.pop(i, None)
            if e.code in (msg.NOT_LEADER, msg.NO_LEADER):
                # same promotion as the general path: the client's
                # _request loop re-routes and resends the whole batch
                # (server-side seq dedup makes the resend exactly-once)
                return msg.CommandBatchResponse(
                    error=e.code, leader=e.leader, error_detail=e.detail)
            return msg.CommandBatchResponse(
                event_index=session.event_index,
                entries=[(seq, 0, None, e.code, e.detail)
                         for seq, _ in entries])
        if trace is not None:
            t2 = time.perf_counter()
            t_commit = self._trace_commit_t.pop(trace, t1)
            self._trace_span(trace, "apply", t_commit, t2,
                             self._m_lat_apply, index=index)
        if self._event_pushes:
            # Events-before-response (reference Consistency.java:157-176):
            # the general path gates each LINEARIZABLE response on its
            # apply's event-push acks inside _complete_command; this lane
            # has no per-seq futures, so gate the block response on the
            # pushes outstanding at commit — a superset of the ones this
            # block's applies spawned — under the same 1 s cap. Empty in
            # the listener-free steady state, so the fast path pays one
            # set check.
            t_push = time.perf_counter() if trace is not None else 0.0
            try:
                await asyncio.wait_for(
                    asyncio.gather(*list(self._event_pushes),
                                   return_exceptions=True), 1.0)
            except asyncio.TimeoutError:
                pass
            if trace is not None:
                self._trace_span(trace, "event.push", t_push,
                                 time.perf_counter(),
                                 self._m_lat_event_push)
        responses = session.responses
        out = []
        for seq, _ in entries:
            cached = responses.get(seq)
            if cached is None:
                # applied without caching: the session died mid-block
                out.append((seq, 0, None, msg.UNKNOWN_SESSION,
                            "session expired before apply"))
            else:
                idx, result, error = cached
                out.append((seq, idx, result,
                            msg.APPLICATION if error else None, error))
        if trace is not None:
            t3 = time.perf_counter()
            self._trace_span(trace, "respond", t2, t3, self._m_lat_respond)
            # stale per-entry marks (entries the vector lane applied or
            # a session death skipped) must not leak
            for i in range(index - len(entries) + 1, index + 1):
                self._trace_entry_marks.pop(i, None)
            self._trace_note_slow(trace, t0, t3)
        return msg.CommandBatchResponse(event_index=session.event_index,
                                        entries=out)

    def _command_response(self, session: ServerSession, index: int,
                          result: Any,
                          error: str | None) -> msg.CommandResponse:
        if error:
            return msg.CommandResponse(error=msg.APPLICATION,
                                       error_detail=error, index=index,
                                       event_index=session.event_index)
        return msg.CommandResponse(index=index, result=result,
                                   event_index=session.event_index)

    # ------------------------------------------------------------------
    # multi-group staging entry points (docs/SHARDING.md): the ingress —
    # local demux or the proxy handler at this group's leader — speaks
    # these instead of the legacy handlers. They accept the GAPPED
    # per-group seq subsequences hash routing produces: delivery order
    # per (session, group) is serialized by the ingress's proxy chain,
    # so appending in arrival order preserves the client's seq order.
    # ------------------------------------------------------------------

    def register_local(self, client_id: str, timeout: float,
                       session_id: int | None = None):
        """Append one RegisterEntry (optionally with a pre-assigned
        global session id — the fan-out from the id-allocating group 0);
        resolves to ``(index, sid, error)``."""
        return self._append_and_wait(
            RegisterEntry(client_id=client_id, timeout=timeout,
                          session_id=session_id))

    def keepalive_local(self, session_id: int, command_seq: int,
                        event_index: int):
        """Append one KeepAliveEntry for this group's session replica
        (``event_index`` is this GROUP's event channel position)."""
        session = self.sessions.get(session_id)
        if session is not None:
            session.last_contact = time.monotonic()
        return self._append_and_wait(KeepAliveEntry(
            session_id=session_id, command_seq=command_seq,
            event_index=event_index))

    def unregister_local(self, session_id: int):
        return self._append_and_wait(
            UnregisterEntry(session_id=session_id, expired=False))

    async def command_block(self, session_id: int, entries: list,
                            trace: int | None = None
                            ) -> tuple[list | None, tuple | None]:
        """Stage one routed (possibly gapped) command sub-block on this
        group's leader; returns ``(per_entry_outcomes, None)`` or
        ``(None, (code, detail, leader))`` for a response-level failure.
        ``trace`` is the originating trace id from the ingress (carried
        by ProxyRequest when proxied): the full per-phase decomposition
        — group.append / quorum.wait / group.fsync / apply / respond —
        records under it on THIS member.

        The dedup walk mirrors ``_stage_command`` minus the dense-seq
        parking: seqs the routing assigned to OTHER groups never arrive
        here, so "the gap will fill" never holds — instead, in-order
        delivery per (session, group) is the ingress's proxy-chain
        contract, and anything below the appended high-water that is not
        cached or in flight is a duplicate."""
        t0 = time.perf_counter() if trace is not None else 0.0
        if self.role != LEADER:
            return None, (msg.NOT_LEADER if self.leader_address
                          else msg.NO_LEADER, "", self.leader_address)
        session = self.sessions.get(session_id)
        if session is None or session.state is not SessionState.OPEN:
            return None, (msg.UNKNOWN_SESSION, "", None)
        session.last_contact = time.monotonic()
        if session.next_append_seq == 0:
            session.next_append_seq = session.command_high + 1
        done: dict[int, tuple] = {}      # seq -> (index, result, error)
        errs: dict[int, tuple] = {}      # seq -> (code, detail)
        waits: dict[int, asyncio.Future] = {}
        fresh: list = []
        for seq, op in entries:
            cached = session.cached_response(seq)
            if cached is not None:
                done[seq] = cached
            elif seq in session.command_futures:
                waits[seq] = session.command_futures[seq]
            elif seq >= session.next_append_seq:
                fresh.append((seq, op))
            elif session.last_block_future is not None \
                    and not session.last_block_future.done():
                # appended by an earlier block still in flight (a client
                # resend racing its first attempt): ride that block's
                # commit and read the cache afterwards
                waits[seq] = None
            else:
                errs[seq] = (msg.INTERNAL,
                             f"response for seq {seq} already pruned")
        self._m_fast_lane.inc(len(fresh))
        block_fut: asyncio.Future | None = None
        index = 0
        t1 = t0
        if fresh:
            term = self.term
            now = time.time()
            index = self.log.append_block(
                [CommandEntry(term, now, session_id, seq, op)
                 for seq, op in fresh])
            self._m_append_block.record(len(fresh))
            session.next_append_seq = fresh[-1][0] + 1
            block_fut = asyncio.get_running_loop().create_future()
            self._commit_futures[index] = block_fut
            session.last_block_future = block_fut
            self._signal_replication()
            if len(self.members) == 1 and not self._advance_scheduled:
                self._advance_scheduled = True
                asyncio.get_running_loop().call_soon(self._advance_deferred)
            if trace is not None:
                t1 = time.perf_counter()
                self._trace_span(trace, "group.append", t0, t1,
                                 self._m_lat_append, index=index,
                                 n=len(fresh))
                self._trace_watch[index] = (trace, t1)
                self._trace_window_marks[index] = trace
                for i in range(index - len(fresh) + 1, index + 1):
                    self._trace_entry_marks[i] = trace
        pending = session.last_block_future
        try:
            if block_fut is not None:
                await block_fut
            elif waits and pending is not None and not pending.done():
                await asyncio.shield(pending)
            for seq, fut in waits.items():
                if fut is not None:
                    await fut
        except msg.ProtocolError as e:
            if trace is not None and fresh:
                self._trace_watch.pop(index, None)
                self._trace_commit_t.pop(trace, None)
                for i in range(index - len(fresh) + 1, index + 1):
                    self._trace_entry_marks.pop(i, None)
            return None, (e.code, e.detail, e.leader)
        t2 = 0.0
        if trace is not None:
            t2 = time.perf_counter()
            if fresh:
                t_commit = self._trace_commit_t.pop(trace, t1)
                self._trace_span(trace, "apply", t_commit, t2,
                                 self._m_lat_apply, index=index)
            else:
                # nothing appended (pure dedup/in-flight waits): the
                # coarse commit span is all there is to attribute
                self._trace_span(trace, "group.commit", t0, t2,
                                 self._m_lat_commit)
        responses = session.responses
        out = []
        for seq, _ in entries:
            if seq in errs:
                code, detail = errs[seq]
                out.append((seq, 0, None, code, detail))
                continue
            cached = done.get(seq) or responses.get(seq)
            if cached is None:
                out.append((seq, 0, None, msg.UNKNOWN_SESSION,
                            "session expired before apply"))
            else:
                idx, result, error = cached
                out.append((seq, idx, result,
                            msg.APPLICATION if error else None, error))
        if trace is not None:
            t3 = time.perf_counter()
            self._trace_span(trace, "respond", t2, t3, self._m_lat_respond)
            if fresh:
                for i in range(index - len(fresh) + 1, index + 1):
                    self._trace_entry_marks.pop(i, None)
            self._trace_note_slow(trace, t0, t3)
        return out, None

    async def serve_query(self, session_id: int, client_index: int,
                          consistency: QueryConsistency, operations: list
                          ) -> tuple[int, list | None, tuple | None]:
        """Serve routed reads on this group (leader for linearizable
        levels, any member for sequential/causal): returns
        ``(served_index, entries, None)`` — entries positional
        ``(result, code, detail)`` — or ``(0, None, (code, detail,
        leader))`` for a request-level refusal."""
        self._m_query_level[consistency.value].inc(len(operations))
        if not self._read_pump:
            request = msg.QueryBatchRequest(
                session_id=session_id, index=client_index,
                consistency=consistency.value, operations=operations)
            response = await self._query_batch_direct(request, consistency)
            if response.error:
                return 0, None, (response.error, response.error_detail or "",
                                 getattr(response, "leader", None))
            return response.index or 0, response.entries, None
        self._m_query_ops.inc(len(operations))
        futs = [self._stage_read(consistency, session_id, client_index, op)
                for op in operations]
        outs = await asyncio.gather(*futs)
        entries = []
        index = 0
        for served_index, result, code, detail in outs:
            if code in (msg.NOT_LEADER, msg.NO_LEADER):
                return 0, None, (code, detail or "", self.leader_address)
            if code and code != msg.APPLICATION:
                return 0, None, (code, detail or "", None)
            entries.append((result, code, detail) if code
                           else (result, None, None))
            index = max(index, served_index)
        return index, entries, None

    # ------------------------------------------------------------------
    # queries: gate + read pump
    # ------------------------------------------------------------------

    async def _gate_query(self, consistency: QueryConsistency,
                          client_index: int) -> tuple[str, str] | None:
        """Consistency-dependent serving precondition; (code, detail) on
        refusal, None once this server may serve at ``last_applied``."""
        if consistency in (QueryConsistency.LINEARIZABLE,
                           QueryConsistency.BOUNDED_LINEARIZABLE):
            if self.role != LEADER:
                return (msg.NOT_LEADER, "")
            if consistency is QueryConsistency.LINEARIZABLE:
                if not await self._confirm_leadership_hook():
                    return (msg.NOT_LEADER, "")
            elif not self._lease_valid():
                if not await self._confirm_leadership_hook():
                    return (msg.NOT_LEADER, "")
            # Serve at the latest committed state.
            await self._wait_applied(self.commit_index)
        else:
            # SEQUENTIAL / CAUSAL: any server, at or after the client's
            # index.
            ok = await self._wait_applied(client_index or 0,
                                          timeout=self.election_timeout * 4)
            if not ok:
                return (msg.INTERNAL, "state lagging behind client index")
        # ``last_applied`` may cover vector rows parked in the server's
        # fused collector — the per-op read lanes behind this gate serve
        # at ``last_applied``, so those device effects must land first
        # (the read WINDOW flushes in ``run_query_window``; a free no-op
        # when nothing is staged)
        self.server.flush_fused()
        return None

    def _edge_seed_response(self, request: Any, response: Any,
                            operations: list) -> Any:
        """Answer a subscribing read (``request.subscribe``, the
        optional trailing field — docs/EDGE_READS.md): register the
        session's edge subscriptions and stamp the seed records onto
        the response's ``edge`` field. A no-op on refusals and on the
        unsubscribed plane (the response stays byte-identical)."""
        if getattr(request, "subscribe", None) and response.ok:
            seeds = self.edge_register(request.session_id, operations,
                                       response.index or 0)
            if seeds:
                response.edge = seeds
        return response

    async def _on_query(self, request: msg.QueryRequest) -> msg.QueryResponse:
        consistency = QueryConsistency(request.consistency or "linearizable")
        self._m_query_level[consistency.value].inc()
        if not self._read_pump:
            return self._edge_seed_response(
                request, await self._query_direct(request, consistency),
                [request.operation])
        self._m_query_ops.inc()
        fut = self._stage_read(consistency, request.session_id,
                               request.index or 0, request.operation)
        index, result, code, detail = await fut
        if code in (msg.NOT_LEADER, msg.NO_LEADER):
            return self._not_leader(msg.QueryResponse)
        if code == msg.APPLICATION:
            return msg.QueryResponse(error=msg.APPLICATION,
                                     error_detail=detail, index=index)
        if code:
            return msg.QueryResponse(error=code, error_detail=detail)
        return self._edge_seed_response(
            request, msg.QueryResponse(index=index, result=result),
            [request.operation])

    async def _query_direct(self, request: msg.QueryRequest,
                            consistency: QueryConsistency
                            ) -> msg.QueryResponse:
        """The per-op read lane (COPYCAT_SERVER_READ_PUMP=0): gate and
        execute this request alone — the pre-pump server bit-identically,
        the readmix A/B baseline."""
        refused = await self._gate_query(consistency, request.index or 0)
        if refused is not None:
            code, detail = refused
            if code == msg.NOT_LEADER:
                return self._not_leader(msg.QueryResponse)
            return msg.QueryResponse(error=code, error_detail=detail)
        session = self.sessions.get(request.session_id)
        commit = Commit(self.last_applied, session, self.context.clock,
                        request.operation, None)
        try:
            result = self.executor.execute(commit)
        except Exception as e:  # noqa: BLE001 - application errors cross
            return msg.QueryResponse(error=msg.APPLICATION,
                                     error_detail=str(e),
                                     index=self.last_applied)
        finally:
            commit.close()
        return msg.QueryResponse(index=self.last_applied, result=result)

    async def _on_query_batch(self, request: msg.QueryBatchRequest
                              ) -> msg.QueryBatchResponse:
        """Batched reads of one consistency level: the gate (leadership
        confirmation / applied wait) runs ONCE for the whole batch — a
        quorum round amortized over N linearizable reads. With the read
        pump on, the batch joins the server-wide per-consistency read
        window, sharing that one gate round with every other session's
        same-turn reads and the device-eligible subset of the window's
        tensor evaluation."""
        consistency = QueryConsistency(request.consistency or "linearizable")
        operations = request.operations or []
        self._m_query_level[consistency.value].inc(len(operations))
        if not self._read_pump or not operations:
            return self._edge_seed_response(
                request,
                await self._query_batch_direct(request, consistency),
                operations)
        self._m_query_ops.inc(len(operations))
        idx = request.index or 0
        futs = [self._stage_read(consistency, request.session_id, idx, op)
                for op in operations]
        outs = await asyncio.gather(*futs)
        entries = []
        index = 0
        for served_index, result, code, detail in outs:
            if code in (msg.NOT_LEADER, msg.NO_LEADER):
                return self._not_leader(msg.QueryBatchResponse)
            if code and code != msg.APPLICATION:
                # gate refusal: identical for every entry of this request
                # (they share index + consistency) — response-level, like
                # the per-op lane
                return msg.QueryBatchResponse(error=code, error_detail=detail)
            if code:
                entries.append((None, code, detail))
            else:
                entries.append((result, None, None))
            index = max(index, served_index)
        return self._edge_seed_response(
            request, msg.QueryBatchResponse(index=index, entries=entries),
            operations)

    async def _query_batch_direct(self, request: msg.QueryBatchRequest,
                                  consistency: QueryConsistency
                                  ) -> msg.QueryBatchResponse:
        """Per-op lane for one batch request (pump off / empty batch)."""
        refused = await self._gate_query(consistency, request.index or 0)
        if refused is not None:
            code, detail = refused
            if code == msg.NOT_LEADER:
                return self._not_leader(msg.QueryBatchResponse)
            return msg.QueryBatchResponse(error=code, error_detail=detail)
        session = self.sessions.get(request.session_id)
        entries = []
        for operation in (request.operations or []):
            commit = Commit(self.last_applied, session, self.context.clock,
                            operation, None)
            try:
                entries.append((self.executor.execute(commit), None, None))
            except Exception as e:  # noqa: BLE001 — per-entry app errors
                entries.append((None, msg.APPLICATION, str(e)))
            finally:
                commit.close()
        return msg.QueryBatchResponse(index=self.last_applied,
                                      entries=entries)

    # -- batched read pump (the read window) ---------------------------

    def _stage_read(self, consistency: QueryConsistency, session_id: int,
                    client_index: int, operation: Any) -> asyncio.Future:
        """Stage one read into the current per-consistency read window;
        resolves to ``(index, result, error_code, error_detail)``. The
        window flushes at the end of the event-loop turn (the same
        call_soon coalescing the client micro-batch uses), so reads
        arriving across sessions and requests in one turn share a gate."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._read_windows.setdefault(consistency.value, []).append(
            (session_id, client_index, operation, fut))
        if not self._read_flush_scheduled:
            self._read_flush_scheduled = True
            loop.call_soon(self._launch_read_windows)
        return fut

    def _launch_read_windows(self) -> None:
        self._read_flush_scheduled = False
        windows, self._read_windows = self._read_windows, {}
        for level, items in windows.items():
            if items:
                spawn(self._flush_read_window(QueryConsistency(level), items),
                      name="read-window")

    @staticmethod
    def _resolve_read(fut: asyncio.Future, payload: tuple) -> None:
        if not fut.done():
            fut.set_result(payload)

    async def _flush_read_window(self, consistency: QueryConsistency,
                                 items: list) -> None:
        try:
            await self._run_read_window(consistency, items)
        except Exception as e:  # noqa: BLE001 — no staged read may hang
            logger.exception("read window failed")
            for _, _, _, fut in items:
                self._resolve_read(fut, (0, None, msg.INTERNAL, str(e)))

    async def _run_read_window(self, consistency: QueryConsistency,
                               items: list) -> None:
        """Serve one read window: the consistency gate ONCE, then the
        reads at an applied snapshot — device-eligible reads as tensors
        through one query_step engine round, the rest through the per-op
        executor lane bit-identically."""
        n = len(items)
        self._m_query_windows.inc()
        self._m_query_window_ops.record(n)
        if consistency in (QueryConsistency.LINEARIZABLE,
                           QueryConsistency.BOUNDED_LINEARIZABLE):
            if self.role != LEADER:
                for _, _, _, fut in items:
                    self._resolve_read(fut, (0, None, msg.NOT_LEADER, ""))
                return
            linear = consistency is QueryConsistency.LINEARIZABLE
            if linear or not self._lease_valid():
                ok = await self._confirm_leadership_hook()
            else:
                ok = True
            if not ok:
                for _, _, _, fut in items:
                    self._resolve_read(fut, (0, None, msg.NOT_LEADER, ""))
                return
            if linear:
                # ONE leadership-confirm round served the whole window;
                # the per-op lane pays one per LINEARIZABLE read — the
                # N-1 amortized rounds are the counter the differential
                # test asserts. Bounded windows never count here: the
                # per-op lane's first confirm renews the lease
                # (_last_quorum_contact), so its reads 2..N are
                # confirm-free too — nothing is actually saved. A failed
                # confirm (refused window) amortizes nothing either.
                self._m_query_gate_saved.inc(n - 1)
            await self._wait_applied(self.commit_index)
            # the gate established the linearization point: serve at it
            # regardless of the client's (necessarily older) index
            self._evaluate_reads(items, check_index=False)
            return
        # SEQUENTIAL / CAUSAL: a read whose own index is already applied
        # serves NOW (the per-op lane's latency — no head-of-line wait
        # behind an unrelated session's lagging index); stragglers share
        # one wait on their max index and refuse per-op at timeout.
        applied = self.last_applied
        ready = [it for it in items if not it[1] or it[1] <= applied]
        lagging = [it for it in items if it[1] and it[1] > applied]
        if ready:
            self._evaluate_reads(ready, check_index=True)
        if lagging:
            await self._wait_applied(max(it[1] for it in lagging),
                                     timeout=self.election_timeout * 4)
            self._evaluate_reads(lagging, check_index=True)

    def _evaluate_reads(self, items: list, check_index: bool) -> None:
        """Serve one batch of gated reads at the current applied
        snapshot. ``check_index`` refuses reads still lagging the
        client's index (a timed-out applied wait) exactly like the
        per-op lane's gate."""
        # ``last_applied`` may cover vector rows still parked in the
        # server's fused collector (their device/host effects land at
        # the turn's one engine round) — reads serve AT last_applied, so
        # those effects must land first (free no-op when nothing staged)
        self.server.flush_fused()
        applied = self.last_applied
        clock = self.context.clock
        route = getattr(self.state_machine, "query_route", None)
        rows: list = []  # (future, machine, instance, inner, spec)
        for session_id, client_index, operation, fut in items:
            if check_index and client_index and client_index > applied:
                self._resolve_read(
                    fut, (0, None, msg.INTERNAL,
                          "state lagging behind client index"))
                continue
            rec = route(operation) if route is not None else None
            if rec is not None:
                rows.append((fut, *rec))
                continue
            self._m_query_per_op.inc()
            session = self.sessions.get(session_id)
            commit = Commit(applied, session, clock, operation, None)
            try:
                result = self.executor.execute(commit)
            except Exception as e:  # noqa: BLE001 — app errors cross
                self._resolve_read(
                    fut, (applied, None, msg.APPLICATION, str(e)))
            else:
                self._resolve_read(fut, (applied, result, None, None))
            finally:
                commit.close()
        if rows:
            self._serve_query_rows(rows, applied)

    def _serve_query_rows(self, rows: list, applied: int) -> None:
        """One query_step engine round for every device-eligible read in
        the window (the read analog of ``_apply_vector_run``): stage [N]
        rows, evaluate from the leader lane's applied state, correlate
        results in a single pass — no per-op Commit objects, no per-op
        executor dispatch."""
        m = len(rows)
        self._m_query_device.inc(m)
        engine = self.state_machine.device_engine
        groups = [0] * m
        opc = [0] * m
        av = [0] * m
        bv = [0] * m
        cv = [0] * m
        for i, (_fut, machine, _inst, _op, spec) in enumerate(rows):
            groups[i] = machine._group
            opc[i], av[i], bv[i], cv[i] = spec[0], spec[1], spec[2], spec[3]
        try:
            raws = engine.run_query_vector(groups, opc, av, bv, cv)
        except Exception as e:  # noqa: BLE001 — fail loudly, never hang
            logger.exception("query vector failed; failing %d reads", m)
            for fut, *_rest in rows:
                self._resolve_read(
                    fut, (applied, None, msg.APPLICATION, str(e)))
            return
        for i, (fut, machine, _inst, inner, spec) in enumerate(rows):
            try:
                result = machine.query_finalize(spec[4], inner, raws[i])
            except Exception as e:  # noqa: BLE001 — app errors cross
                self._resolve_read(
                    fut, (applied, None, msg.APPLICATION, str(e)))
            else:
                self._resolve_read(fut, (applied, result, None, None))

    async def _wait_applied(self, index: int,
                            timeout: float | None = None) -> bool:
        deadline = (time.monotonic() + timeout) if timeout else None
        while self.last_applied < index:
            self._applied_event.clear()
            remaining = None if deadline is None \
                else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                return False
            try:
                await asyncio.wait_for(self._applied_event.wait(), remaining)
            except asyncio.TimeoutError:
                return False
        return True

    # ------------------------------------------------------------------
    # apply loop
    # ------------------------------------------------------------------

    def _apply_up_to(self, commit_index: int) -> None:
        t_replay = time.perf_counter() if self._recovery_boot_last else 0.0
        window = None
        route = None
        if self.last_applied < commit_index:
            begin = getattr(self.state_machine, "begin_window", None)
            if begin is not None:
                window = begin()  # None on the CPU executor
            if window is not None and self._vector_pump:
                route = getattr(self.state_machine, "vector_route", None)
        key_fn = None
        if route is not None:
            self._m_apply_window.record(commit_index - self.last_applied)
            if self._parallel_apply:
                # dependency-classified windows (docs/SHARDING.md "Apply
                # ordering"): runs span ineligible entries on disjoint
                # keys; COPYCAT_PARALLEL_APPLY=0 (or a state machine
                # without apply_key) keeps the contiguous classifier
                key_fn = getattr(self.state_machine, "apply_key", None)
        vrun: list = []  # staged rows: (clock, entry, session, *route rec)
        # Timer deadline for the classify gate, recomputed only after
        # entries that can (un)schedule timers — the per-entry
        # ``next_deadline()`` heap peek was a measured share of the
        # classify walk. A vector run itself never moves it (eligibility
        # excludes TTL ops, and its tick fires nothing by the gate).
        deadline = self.executor.next_deadline() if route is not None else None
        try:
            while self.last_applied < commit_index:
                index = self.last_applied + 1
                entry = self.log.get(index)
                self.last_applied = index
                if entry is None:
                    continue
                if route is not None and type(entry) is CommandEntry:
                    rec = self._vector_classify(entry, route, deadline)
                    if rec is not None:
                        # Advance the log clock AT STAGE TIME: inline
                        # entries applied while this row waits must see
                        # the clock the sequential walk would (timer
                        # gates, commit times); the row carries its own
                        # clock so finalization stamps the sequential
                        # per-entry value even after later entries
                        # advanced the context further.
                        if entry.timestamp > self.context.clock:
                            self.context.clock = entry.timestamp
                        if key_fn is not None:
                            self._stage_keys.add(key_fn(entry.operation))
                            self._stage_sessions.add(entry.session_id)
                        vrun.append((self.context.clock, *rec))
                        continue
                    self._m_vector_refused.inc()
                if vrun or self._stage_rows:
                    # An ineligible entry bounds the staged run — always
                    # on the contiguous plane (key_fn None), only on a
                    # dependency/session/timer conflict on the parallel
                    # plane (a disjoint-key entry is spanned; per-key
                    # FIFO still holds because a colliding entry forces
                    # the dispatch below BEFORE it applies). vrun is
                    # emptied BEFORE the call — if the run raises
                    # (window barrier timeout), replaying it at the next
                    # flush point would double-apply. Its try is
                    # SEPARATE from the bounding entry's: a failed run
                    # must not swallow the entry's apply (last_applied
                    # already advanced past it; skipping it would hang
                    # its commit future and, for a config entry, diverge
                    # this replica's membership view).
                    if key_fn is None or self._apply_conflicts(
                            entry, key_fn, deadline):
                        if key_fn is not None:
                            self._m_apply_conflicts.inc()
                        run, vrun = vrun, []
                        try:
                            self._bound_vector_run(run, window)
                        except Exception:
                            logger.exception(
                                "vector apply failed before index %d", index)
                    else:
                        # spanned: rows are staged locally (vrun) or
                        # parked in the fused collector (_stage_rows) —
                        # the outer guard admits no third case
                        self._m_apply_spans.inc()
                try:
                    self._apply_entry(entry, window)
                except Exception:
                    logger.exception("apply failed at index %d", index)
                if route is not None:
                    deadline = self.executor.next_deadline()
            if vrun:
                try:
                    self._stage_vector_tail(vrun, window)
                except Exception:
                    logger.exception("vector apply failed")
        finally:
            if window is not None:
                try:
                    window.close()
                except Exception:
                    logger.exception("device window close failed")
        if self._recovery_boot_last:
            # boot-tail replay accounting: cumulative apply time until the
            # restart's surviving log tail is fully re-applied — the
            # number the snapshot cadence bounds (snap.recovery_replay_ms)
            self._recovery_replay_s += time.perf_counter() - t_replay
            if self.last_applied >= self._recovery_boot_last:
                self.metrics.gauge("snap.recovery_replay_ms").set(
                    self._recovery_replay_s * 1e3)
                self._recovery_boot_last = 0
        self._applied_event.set()
        self._maybe_snapshot()

    # -- batched server-side pump (the vector lane) --------------------

    # The engine's terminal-refusal sentinel (``ops.apply.FAIL``), as a
    # literal so server/ stays import-independent of the jax-backed ops
    # package. ``_devint`` excludes INT32_MIN from payloads, so no
    # legitimate device result ever collides with it.
    _DEVICE_FAIL = -(2 ** 31)

    def _vector_classify(self, entry: CommandEntry, route: Any,
                         deadline: float | None):
        """One staged row for the vector run, or ``None`` for the
        per-entry path. Eligibility repeats the windowed apply's
        exactly-once guards (duplicates and dead sessions always take
        the general path, which serves cached responses) and refuses
        whenever a state-machine timer would fire within the run (tick
        order must match the per-entry walk on every replica).

        The ``command_high`` dedup is safe against SAME-seq entries
        appearing twice in one classify walk because cross-term
        duplicates (old leader appended, client resent to the new one)
        are always separated in the log by the new leader's takeover
        ``NoOpEntry`` (Raft §5.4.2, ``_become_leader``) — an ineligible
        entry that bounds the run, applying the first instance (and
        advancing ``command_high``) before the resend is classified.
        Same-leader duplicates never double-append at all
        (``_stage_command`` shares the in-flight future).
        ``deadline`` is the caller's cached ``executor.next_deadline()``
        (valid for the whole contiguous classify walk)."""
        session = self.sessions.get(entry.session_id)
        if session is None or session.state is not SessionState.OPEN:
            return None
        seq = entry.seq
        if seq and (seq <= session.command_high
                    or (entry.session_id, seq) in self._window_pending_seqs):
            return None
        rec = route(entry.operation)
        if rec is None:
            return None
        if deadline is not None \
                and deadline <= max(self.context.clock, entry.timestamp):
            return None
        return (entry, session, *rec)

    def _apply_conflicts(self, entry: Entry, key_fn: Any,
                         deadline: float | None) -> bool:
        """Does applying ``entry`` inline conflict with the staged vector
        rows? The monotone-tag gate of the dependency-classified plane
        (docs/SHARDING.md "Apply ordering"): a staged run may be spanned
        by this entry only when the entry provably touches none of the
        run's resources, sessions, or timers — anything else forces the
        staged effects to land FIRST, preserving per-key (and
        per-session) FIFO exactly as the sequential walk would.

        Conflicts, conservatively:
        - timer adjacency: this entry's tick could fire a state-machine
          timer (timers touch arbitrary resources);
        - non-command entries: register/keepalive/unregister/config/noop
          read or mutate session and membership state broadly (and the
          takeover ``NoOpEntry`` flush is what keeps the classify-time
          duplicate-seq argument valid — see ``_vector_classify``);
        - same session: response cache order, keepalive clocks, and the
          cached-response dedup all require per-session FIFO;
        - same or unclassifiable key: ``apply_key`` returns ``None`` for
          catalog ops (create/get/delete reshape the catalog itself) —
          the whole-window barrier."""
        if deadline is not None \
                and deadline <= max(self.context.clock, entry.timestamp):
            return True
        if type(entry) is not CommandEntry:
            return True
        if entry.session_id in self._stage_sessions:
            return True
        key = key_fn(entry.operation)
        return key is None or key in self._stage_keys

    def _bound_vector_run(self, run: list, window: Any) -> None:
        """Dispatch every staged row at a conflict bound: the bounding
        entry applies only after the staged device effects land. On the
        fused plane this forces the SERVER's collector synchronously
        (other groups' staged rows ride along in the same engine round);
        per-group otherwise."""
        if self._apply_fuse:
            if run:
                self._stage_fused(run)
            self.server.flush_fused()
        elif run:
            self._apply_vector_run(run, window)

    def _stage_vector_tail(self, run: list, window: Any) -> None:
        """End-of-window dispatch point: on the fused plane the run
        parks in the server's collector and rides the turn's ONE engine
        round (``RaftServer.flush_fused``); per-group it dispatches
        now."""
        if self._apply_fuse:
            self._stage_fused(run)
        else:
            self._apply_vector_run(run, window)

    def _stage_fused(self, run: list) -> None:
        """Hand one run to the server's cross-group collector.
        ``_stage_rows`` counts this group's parked rows so the next
        ``_apply_up_to`` window still bounds them on conflict (its local
        ``vrun`` starts empty but the dependency sets persist)."""
        self._stage_rows += len(run)
        self.server.stage_vector_run(self, run)

    def _apply_vector_run(self, run: list, window: Any) -> None:
        """Apply one run of vector-eligible commands on the PER-GROUP
        lane (``COPYCAT_APPLY_FUSE=0``): ONE vectorized engine round for
        the whole run (``DeviceEngine.run_vector``), then per-entry
        finalization in log order via :meth:`_finalize_vector_run` —
        with zero generator/window machinery per op. A barrier failure
        is a pump error (rows fail explicitly, futures resolve) instead
        of an exception that would silently drop the run."""
        raws, pump_error = dispatch_vector_rows(
            self.state_machine.device_engine, window, run)
        self._finalize_vector_run(run, raws, pump_error)

    def _finalize_vector_run(self, run: list, raws: list,
                             pump_error: str | None) -> None:
        """Per-entry finalization of one DISPATCHED run in log order —
        response cache, commit futures, held-commit bookkeeping — shared
        by the per-group lane (:meth:`_apply_vector_run`) and the
        server's fused cross-group dispatch (``RaftServer.flush_fused``).

        A failed pump (``pump_error`` set) takes an EXPLICIT per-entry
        failure branch: ``raws`` is never indexed (it is empty then —
        the old guard-path walked ``raws[k]`` behind a short-circuit),
        every entry's future resolves with the error, and the log slot
        is cleaned, so a mid-run engine failure degrades to N failed
        commands instead of N hung futures."""
        n = len(run)
        self._m_vector_runs.inc()
        self._m_vector_ops.inc(n)
        self._m_run_length.record(n)
        log = self.log
        futures = self._commit_futures
        marks = self._trace_entry_marks
        for k, (clock, entry, session, machine, instance, inner, spec) in \
                enumerate(run):
            trace = marks.pop(entry.index, None) if marks else None
            if self._edge_subs:
                # the vector lane mutates device resources too: dirty
                # them for the turn's edge-delta flush (which flushes
                # the fused collector before serializing states)
                self._edge_note_apply(entry, trace)
            if pump_error is not None:
                result, error = None, pump_error
                log.clean(entry.index)
            elif raws[k] == self._DEVICE_FAIL:
                # the tracked fallback lane can surface the engine's
                # refusal sentinel (a group emptied by a config change
                # mid-run); legitimate results never equal it (_devint
                # excludes INT32_MIN), and handing it to vector_finalize
                # would record a refused op as a committed result
                result, error = None, "device refused the operation"
                log.clean(entry.index)
            else:
                # the row's own staged clock (the sequential per-entry
                # value), not the context clock — later entries may have
                # advanced the context past this row's log slot
                commit = Commit(entry.index, instance.session, clock, inner,
                                log)
                try:
                    result: Any = machine.vector_finalize(
                        spec[4], inner, raws[k], commit)
                    error: str | None = None
                except Exception as e:  # noqa: BLE001 — app errors cross
                    result, error = None, str(e)
                    log.clean(entry.index)
            seq = entry.seq
            if seq:
                session.last_keepalive_time = clock
                session.cache_response(seq, entry.index, result, error)
            fut = futures.pop(entry.index, None)
            if fut is not None and not fut.done():
                fut.set_result((entry.index, result, error))
            if seq and session.command_futures:
                self._complete_command(entry, result, error, [])
        # dependency bookkeeping: this run's rows are no longer staged.
        # The collector drains whole (never partially), so a zero count
        # retires the key/session sets; the per-group lane enters with
        # _stage_rows == 0 and clears them here too.
        if self._stage_rows > n:
            self._stage_rows -= n
        else:
            self._stage_rows = 0
            if self._stage_keys:
                self._stage_keys.clear()
            if self._stage_sessions:
                self._stage_sessions.clear()
        self.executor.tick(self.context.clock)  # fires nothing (classify
        # gate: every staged row's clock precedes every pending deadline)

    def _apply_entry(self, entry: Entry, window: Any = None) -> None:
        self._m_apply_entry.inc()
        if (window is not None and window.busy
                and not isinstance(entry, CommandEntry)):
            # Session/config/noop entries read state that in-flight device
            # chains may still mutate — drain the window to stay aligned
            # with the log on every server.
            window.barrier()
        self.context.index = entry.index
        self.context.clock = max(self.context.clock, entry.timestamp)
        # originating trace for this entry, when its staging marked one
        # (empty-dict truthiness is the whole untraced cost): events the
        # apply publishes ride PublishRequest under the same id — popped
        # BEFORE the windowed-lane branch so device-backed applies
        # neither leak marks nor lose event attribution
        marks = self._trace_entry_marks
        trace = marks.pop(entry.index, None) if marks else None
        if window is not None and isinstance(entry, CommandEntry):
            self._apply_command_windowed(entry, window, trace)
            return
        # Reset BEFORE ticking: timer callbacks publish session events too,
        # and those must be sealed/pushed with this entry.
        self._touched_sessions = set()
        self.executor.tick(self.context.clock)

        result: Any = None
        error: str | None = None
        if isinstance(entry, RegisterEntry):
            result = self._apply_register(entry)
        elif isinstance(entry, KeepAliveEntry):
            self._apply_keepalive(entry)
        elif isinstance(entry, UnregisterEntry):
            self._apply_unregister(entry)
        elif isinstance(entry, CommandEntry):
            result, error, _ = self._apply_command(entry)
        elif isinstance(entry, ConfigurationEntry):
            self._apply_configuration(entry)
        elif isinstance(entry, NoOpEntry):
            self.log.clean(entry.index)

        # Seal + push session events produced by this entry.
        pushes = self._seal_and_push(self._touched_sessions, trace)

        fut = self._commit_futures.pop(entry.index, None)
        if fut is not None and not fut.done():
            fut.set_result((entry.index, result, error))
        if isinstance(entry, CommandEntry):
            if self._edge_subs:
                self._edge_note_apply(entry, trace)
            self._complete_command(entry, result, error, pushes)

    def _seal_and_push(self, touched,
                       trace: int | None = None) -> list[asyncio.Task]:
        pushes: list[asyncio.Task] = []
        for session in touched:
            batch = session.commit_events()
            if batch is None:
                continue
            # Single-group: only the leader pushes (it owns the client
            # connection). Multi-group: the member HOLDING the session's
            # connection pushes — that is the ingress, which may be a
            # follower of this group applying the replicated entry; the
            # group's leader has no connection and skips (docs/SHARDING.md
            # "event channels").
            if (self.role == LEADER if self.server.single
                    else session.connection is not None):
                task = self._push_events(session, trace)
                if task is not None:
                    pushes.append(task)
                    self._event_pushes.add(task)
                    task.add_done_callback(self._event_pushes.discard)
        return pushes

    # -- windowed apply (device executor) ------------------------------

    def _apply_command_windowed(self, entry: CommandEntry, window: Any,
                                trace: int | None = None) -> None:
        """Apply one command entry under the device window: the handler may
        return a suspended device-op chain (DeviceJob) that is deferred
        into the shared round pump; its finalization (response cache,
        event seal/push, futures) runs at the entry's log-ordered slot."""
        ctx = _EntryCtx(self, entry, trace)
        window.job_ctx = ctx  # timer chains spawned by tick inherit it
        try:
            with ctx:
                self.executor.tick(self.context.clock)
                result, error, job = self._apply_command(entry, window)
        finally:
            window.job_ctx = None
        if job is not None:
            window.add_job(job, ctx=ctx, on_done=lambda res, exc:
                           self._finalize_deferred(entry, res, exc, ctx))
        else:
            window.add_ready(lambda res, exc:
                             self._finalize_entry(entry, result, error, ctx))

    def _finalize_deferred(self, entry: CommandEntry, result: Any,
                           exc: BaseException | None,
                           ctx: "_EntryCtx") -> None:
        error: str | None = None
        if exc is not None:
            result, error = None, str(exc)
            self.log.clean(entry.index)
        if entry.seq:
            self._window_pending_seqs.discard((entry.session_id, entry.seq))
            session = self.sessions.get(entry.session_id)
            if session is not None:
                session.cache_response(entry.seq, entry.index, result, error)
        self._finalize_entry(entry, result, error, ctx)

    def _finalize_entry(self, entry: CommandEntry, result: Any,
                        error: str | None, ctx: "_EntryCtx") -> None:
        ctx.replay()  # buffered publishes land in log order
        pushes = self._seal_and_push(ctx.touched, ctx.trace)
        fut = self._commit_futures.pop(entry.index, None)
        if fut is not None and not fut.done():
            fut.set_result((entry.index, result, error))
        if self._edge_subs:
            self._edge_note_apply(entry, ctx.trace)
        self._complete_command(entry, result, error, pushes)

    def _session_touched(self, session: ServerSession) -> None:
        self._touched_sessions.add(session)

    def _apply_register(self, entry: RegisterEntry) -> int:
        # Session id: the registering entry's log index on the
        # single-group plane (the reference rule, bit-identical); on the
        # multi-group plane the id-allocating group 0 derives a globally
        # unique id (index stamped with the group count) and the fan-out
        # entries to groups 1..G-1 carry it explicitly, so EVERY group's
        # replica of one client session shares one id (docs/SHARDING.md).
        sid = getattr(entry, "session_id", None)
        if not sid:
            sid = (entry.index if self.server.single
                   else entry.index * self.server.num_groups)
        session = ServerSession(sid, entry.client_id, entry.timeout)
        session.last_keepalive_time = self.context.clock
        # Wire publish -> touched-session tracking for this apply step.
        self._wire_session(session)
        self.sessions[sid] = session
        if self.role == LEADER:
            session.last_contact = time.monotonic()
        if not self.server.single:
            # late-bind the client's connection (docs/SHARDING.md): the
            # ingress member may have touched this session before our
            # follower apply created the replica — the ingress, not the
            # group leader, owns this session's event channel
            conn = self.server._session_conns.get(sid)
            if conn is not None and not conn.closed:
                session.connection = conn
                session.last_contact = time.monotonic()
        self.state_machine.register(session)
        return sid

    def _apply_keepalive(self, entry: KeepAliveEntry) -> None:
        session = self.sessions.get(entry.session_id)
        if session is None:
            return
        session.last_keepalive_time = self.context.clock
        session.ack_commands(entry.command_seq or 0)
        session.ack_events(entry.event_index or 0)
        self.log.clean(entry.index)

    def _apply_unregister(self, entry: UnregisterEntry) -> None:
        session = self.sessions.pop(entry.session_id, None)
        self._expiring_sessions.discard(entry.session_id)
        if self._edge_sessions:
            self._edge_drop_session(entry.session_id)
        if not self.server.single and self.group_id == 0:
            # the metadata group's unregister retires the server-level
            # connection binding (the late-bind map would otherwise pin
            # one entry per session forever)
            self.server._session_conns.pop(entry.session_id, None)
        if session is None:
            self.log.clean(entry.index)
            return
        self.metrics.counter(
            "sessions_expired_total" if entry.expired
            else "sessions_closed_total").inc()
        if entry.expired:
            session.expire()
            self.state_machine.expire(session)
        else:
            session.close()
        self.state_machine.close(session)
        session.state = (SessionState.EXPIRED if entry.expired
                         else SessionState.CLOSED)
        self.log.clean(entry.index)

    def _apply_command(self, entry: CommandEntry,
                       window: Any = None) -> tuple[Any, str | None, Any]:
        """Apply one command; returns ``(result, error, deferred_job)``.

        ``deferred_job`` is non-None only under an open device window, when
        the handler returned a suspended device-op chain: the caller owns
        its response caching and completion (``_finalize_deferred``)."""
        session = self.sessions.get(entry.session_id)
        if session is None or session.state is not SessionState.OPEN:
            self.log.clean(entry.index)
            return None, "session expired or unknown", None
        if (entry.seq and window is not None
                and (entry.session_id, entry.seq)
                in self._window_pending_seqs):
            # duplicate of a command still in flight in this window: settle
            # it first so the cached-response dedup below sees it
            window.barrier()
        if entry.seq and entry.seq <= session.command_high:
            cached = session.cached_response(entry.seq)
            if cached is not None:
                _, result, error = cached
                return result, error, None
            # Duplicate append whose cached response was already pruned; the
            # original apply completed any pending future, so this error
            # result is only ever seen if something is deeply wrong — never
            # a silent success for a skipped write.
            return None, \
                f"duplicate command seq {entry.seq} (response pruned)", None
        session.last_keepalive_time = self.context.clock
        commit = Commit(entry.index, session, self.context.clock,
                        entry.operation, self.log)
        try:
            result, error = self.executor.execute(commit), None
        except Exception as e:  # noqa: BLE001
            result, error = None, str(e)
            self.log.clean(entry.index)
        if getattr(result, "is_device_job", False):
            if window is not None:
                if entry.seq:
                    self._window_pending_seqs.add(
                        (entry.session_id, entry.seq))
                return None, None, result
            # no window open (state machine hosted outside the manager's
            # apply loop): drive the chain alone
            try:
                result, error = result.run(), None
            except Exception as e:  # noqa: BLE001
                result, error = None, str(e)
                self.log.clean(entry.index)
        if entry.seq:
            session.cache_response(entry.seq, entry.index, result, error)
        return result, error, None

    def _apply_configuration(self, entry: ConfigurationEntry) -> None:
        self._adopt_members(entry.members)
        self.log.clean(entry.index)
        if not self.server.single and self.group_id == 0:
            # membership rides the metadata group's log (docs/SHARDING.md):
            # the server propagates the applied view to groups 1..G-1,
            # which adopt it and reconcile their replication streams
            self.server._membership_applied(self.members)

    def _adopt_members(self, members: list[Address]) -> None:
        """Install a membership view and reconcile the leader's
        replication streams (the apply path for this group's own
        ConfigurationEntry, and the propagation path from the metadata
        group on a multi-group server)."""
        self.members = list(members)
        if self.role == LEADER:
            for peer in self.peers:
                if peer not in self._replication_tasks:
                    self.next_index[peer] = self.log.last_index + 1
                    self.match_index[peer] = 0
                    self._replication_events[peer] = asyncio.Event()
                    self._replication_tasks[peer] = spawn(
                        self._replicate_loop(peer),
                        name=f"replicate-{peer}")
            for peer in list(self._replication_tasks):
                if peer not in self.members:
                    self._replication_tasks.pop(peer).cancel()
                    self._replication_events.pop(peer, None)

    def _complete_command(self, entry: CommandEntry, result: Any,
                          error: str | None,
                          pushes: list[asyncio.Task]) -> None:
        session = self.sessions.get(entry.session_id)
        if session is None:
            return
        fut = session.command_futures.get(entry.seq)
        if fut is None or fut.done():
            return
        operation = entry.operation
        consistency = (operation.consistency()
                       if isinstance(operation, Command)
                       else CommandConsistency.LINEARIZABLE)
        payload = (entry.index, result, error)
        if pushes and consistency is CommandConsistency.LINEARIZABLE:
            # Events-before-response: the response releases only after event
            # pushes are acknowledged (reference Consistency.java:157-176).
            async def complete_after_events() -> None:
                try:
                    await asyncio.wait_for(
                        asyncio.gather(*pushes, return_exceptions=True), 1.0)
                except asyncio.TimeoutError:
                    pass
                if not fut.done():
                    fut.set_result(payload)

            spawn(complete_after_events(), name="events-before-response")
        else:
            fut.set_result(payload)

    # ------------------------------------------------------------------
    # event push (connection-holder only; leader == holder when single)
    # ------------------------------------------------------------------

    def _push_events(self, session: ServerSession,
                     trace: int | None = None) -> asyncio.Task | None:
        if session.connection is None or session.connection.closed:
            return None
        return spawn(self._flush_events_async(session, trace),
                     name="event-push")

    def _flush_events(self, session: ServerSession) -> None:
        self._push_events(session)

    async def _flush_events_async(self, session: ServerSession,
                                  trace: int | None = None) -> None:
        conn = session.connection
        if conn is None or conn.closed:
            return
        t0 = time.perf_counter() if trace is not None else 0.0
        pushed = False
        try:
            for batch in list(session.event_queue):
                if batch.event_index <= session.event_ack_index:
                    continue
                try:
                    response = await asyncio.wait_for(
                        conn.send(msg.PublishRequest(
                            session_id=session.id,
                            event_index=batch.event_index,
                            prev_event_index=batch.prev_event_index,
                            events=batch.events,
                            group=self.wire_group, trace=trace)),
                        1.0)
                except (TransportError, OSError, asyncio.TimeoutError):
                    return
                pushed = True
                if response.event_index is not None:
                    session.ack_events(response.event_index)
                    if response.event_index < batch.event_index:
                        # client is behind; caught up on the next pass
                        return
        finally:
            # any completed push (including one before a catching-up
            # early return) is timeline-worthy — an asymmetric trace
            # with a client.event but no event.push reads as a hole
            if trace is not None and pushed:
                self._trace_span(trace, "event.push", t0,
                                 time.perf_counter(),
                                 self._m_lat_event_push)

    # ------------------------------------------------------------------
    # edge read tier: subscriber registry + delta publication
    # (docs/EDGE_READS.md — deltas ride the same PublishRequest plane as
    # the event channels above, pushed by the same connection holder,
    # but need NO position in the gap/replay machinery: the client's
    # join-semilattice merge makes duplicated/reordered/re-delivered
    # deltas converge instead of corrupting)
    # ------------------------------------------------------------------

    def edge_register(self, session_id: int, operations: list,
                      version: int) -> list | None:
        """Register edge subscriptions for a subscribing read served at
        (group-local) ``version`` and build the response's seed records
        ``[(instance_id, version, state), ...]``; ``None`` when this
        member cannot feed deltas (edge tier off, no live session
        connection here, nothing edge-eligible in ``operations``)."""
        if not self.server._edge_enabled:
            return None
        session = self.sessions.get(session_id)
        if session is None or session.connection is None \
                or session.connection.closed:
            return None
        locate = getattr(self.state_machine, "edge_locate", None)
        state_of = getattr(self.state_machine, "edge_state_of", None)
        if locate is None or state_of is None:
            return None
        seeds: list = []
        for op in operations:
            loc = locate(op)
            if loc is None:
                continue
            rid, iid = loc
            try:
                state = state_of(rid)
            except Exception:  # noqa: BLE001 — a seed must never fail a read
                logger.exception("edge seed for resource %d failed", rid)
                continue
            if state is NotImplemented or state is None:
                continue
            self._edge_subs.setdefault(rid, {}).setdefault(
                session_id, set()).add(iid)
            self._edge_sessions.setdefault(session_id, set()).add(rid)
            self._m_edge_subscribes.inc()
            seeds.append((iid, version, state))
        if seeds:
            self._refresh_edge_gauge()
        return seeds or None

    def edge_unsubscribe(self, session_id: int, instance_ids) -> None:
        """Retire a client's evicted instances (the keep-alive's
        ``unsubscribe`` field) from the registry."""
        rids = self._edge_sessions.get(session_id)
        if not rids:
            return
        drop = set(instance_ids)
        removed = 0
        for rid in list(rids):
            subs = self._edge_subs.get(rid)
            iids = subs.get(session_id) if subs else None
            if not iids:
                continue
            n = len(iids)
            iids -= drop
            removed += n - len(iids)
            if not iids:
                subs.pop(session_id, None)
                rids.discard(rid)
                if not subs:
                    self._edge_subs.pop(rid, None)
        if not rids:
            self._edge_sessions.pop(session_id, None)
        if removed:
            self._m_edge_unsubscribes.inc(removed)
            self._refresh_edge_gauge()

    def _edge_drop_session(self, session_id: int) -> None:
        """Session death (close/expiry apply) retires every
        subscription it held."""
        rids = self._edge_sessions.pop(session_id, None)
        if not rids:
            return
        for rid in rids:
            subs = self._edge_subs.get(rid)
            if subs is not None:
                subs.pop(session_id, None)
                if not subs:
                    self._edge_subs.pop(rid, None)
        self._refresh_edge_gauge()

    def _refresh_edge_gauge(self) -> None:
        self._m_edge_subs.set(sum(
            len(iids) for subs in self._edge_subs.values()
            for iids in subs.values()))

    def _edge_note_apply(self, entry: "CommandEntry",
                         trace: int | None = None) -> None:
        """Mark the resource a just-applied command mutated dirty for
        this turn's delta flush. The empty-registry truthiness check at
        every call site is the whole cost when nothing subscribed (and
        with COPYCAT_EDGE_READS=0 nothing ever registers)."""
        key_fn = getattr(self.state_machine, "apply_key", None)
        rid = key_fn(entry.operation) if key_fn is not None else None
        if rid is None:
            # unclassifiable footprint (catalog create/get/delete may
            # reshape any resource): conservatively dirty every
            # subscribed resource — the flush re-reads their states and
            # retires the ones that are gone
            for r in self._edge_subs:
                self._edge_dirty.setdefault(r, trace)
        elif rid in self._edge_subs:
            self._edge_dirty[rid] = trace
        if not self._edge_dirty or self._edge_flush_scheduled:
            return
        self._edge_flush_scheduled = True
        try:
            loop = asyncio.get_running_loop()
            if self._edge_flush_s > 0:
                loop.call_later(self._edge_flush_s, self._edge_flush)
            else:
                loop.call_soon(self._edge_flush)
        except RuntimeError:
            # synchronous replay harness: no loop, nothing to push to
            self._edge_flush_scheduled = False
            self._edge_dirty.clear()

    def _edge_flush(self) -> None:
        """End-of-turn delta publication: serialize each dirty
        resource's post-apply state ONCE and push it to every local
        subscriber. A hot resource written many times in one turn
        coalesces to one delta; versions stamp the group's
        ``last_applied``, so a merging replica may serve any read its
        per-group index admits up to that point (the state of a
        resource at ``last_applied`` IS its state after its own last
        write — later entries in the turn touched other resources)."""
        self._edge_flush_scheduled = False
        if not self._edge_dirty or self._closing:
            self._edge_dirty.clear()
            return
        state_of = getattr(self.state_machine, "edge_state_of", None)
        if state_of is None:
            return
        # staged-but-undispatched fused vector rows are device effects
        # the serialized states must include — and their finalization
        # dirties MORE resources, so the collector must drain BEFORE
        # the dirty set is snapshotted: a fused write landing after the
        # swap would be certified "unchanged" by this flush's refresh
        # records at a version covering it (free no-op when empty)
        self.server.flush_fused()
        dirty, self._edge_dirty = self._edge_dirty, {}
        version = self.last_applied
        # one push carries ONE trace (the first dirty entry's) — the
        # replication-window sampling limitation, documented there
        trace = next((t for t in dirty.values() if t is not None), None)
        pushes: dict[int, list] = {}
        sessions: dict[int, ServerSession] = {}
        for rid in dirty:
            subs = self._edge_subs.get(rid)
            if not subs:
                continue
            try:
                state = state_of(rid)
            except Exception:  # noqa: BLE001 — publication must not wound apply
                logger.exception("edge state for resource %d failed", rid)
                state = None
            if state is NotImplemented:
                state = None
            for sid, iids in list(subs.items()):
                session = self.sessions.get(sid)
                if session is None:
                    continue
                pushes.setdefault(sid, []).extend(
                    (iid, version, state) for iid in iids)
                sessions[sid] = session
            if state is None:
                # resource gone (deleted / stopped being edge-servable):
                # the None deltas above retire the client entries; drop
                # the registry side too
                self._m_edge_retired.inc()
                for sid in list(subs):
                    self.edge_unsubscribe(sid, list(subs.get(sid, ())))
        if not pushes:
            return
        self._m_edge_flushes.inc()
        for sid, recs in pushes.items():
            session = sessions[sid]
            conn = session.connection
            if conn is None or conn.closed:
                # cannot certify delivery for this session any more:
                # retire its subscriptions in this group — a re-bound
                # connection resuming pushes after a gap would certify
                # currency over deltas the gap swallowed (the client
                # TTLs out and re-seeds instead)
                self._edge_drop_session(sid)
                continue
            # version-refresh records for the session's OTHER subscribed
            # resources: this flush touched none of them, so their last
            # certified state is still current at `version` — the
            # explicit per-resource currency certification the client's
            # monotone gate consumes (docs/EDGE_READS.md). Without it a
            # client whose read floor rose (any server read) would
            # stale-reject every warm entry forever.
            dirty_iids = {iid for iid, _, _ in recs}
            for rid in self._edge_sessions.get(sid, ()):
                if rid in dirty:
                    continue
                for iid in self._edge_subs.get(rid, {}).get(sid, ()):
                    if iid not in dirty_iids:
                        recs.append((iid, version, _EDGE_REFRESH))
            self._m_edge_deltas.inc(len(recs))
            task = spawn(self._edge_push(conn, session, recs, trace),
                         name="edge-push")
            self._edge_pushes.add(task)
            task.add_done_callback(self._edge_pushes.discard)

    async def _edge_push(self, conn: Connection, session: ServerSession,
                         recs: list, trace: int | None) -> None:
        try:
            await asyncio.wait_for(conn.send(msg.PublishRequest(
                session_id=session.id, event_index=None,
                prev_event_index=None, events=None,
                group=self.wire_group, trace=trace, deltas=recs)), 1.0)
        except (TransportError, OSError, asyncio.TimeoutError):
            # delivery unknown: stop certifying for this session — its
            # replica TTLs out and re-seeds; resumed pushes over a
            # possibly-lossy gap could otherwise certify stale state
            self._edge_drop_session(session.id)

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def health_sample(self) -> dict:
        """One point-in-time sample for the health monitor's detectors
        (``utils/health.py``): cursors, churn counters, replication
        stream windows, fsync latency accounting, and session-plane
        signals. ``fsync_max_ms`` is consume-on-read: the max since the
        previous sample."""
        m = self.metrics
        recent = self._fsync_recent_max_ms
        self._fsync_recent_max_ms = 0.0
        return {
            "role": self.role,
            "term": self.term,
            "commit_index": self.commit_index,
            "last_applied": self.last_applied,
            "log_last_index": self.log.last_index,
            "elections": m.counter("raft_elections_started").value,
            "transitions": m.counter("raft_leader_transitions").value,
            "rewinds": self._m_repl_rewinds.value,
            "stalls": self._m_repl_stalls.value,
            "repl_windows": {str(p): (s.window, s.floor, s.floor_hits)
                             for p, s in self._peer_streams.items()},
            "fsyncs": self._fsync_count,
            "fsync_max_ms": recent,
            "fsync_ewma_ms": self._fsync_ewma_ms,
            "sessions_expired": m.counter("sessions_expired_total").value,
            "event_backlog": sum(len(s.event_queue)
                                 for s in self.sessions.values()),
            "snap_failures": (self._m_snap_capture_fail.value
                              + self._m_snap_install_fail.value),
        }

    def refresh_gauges(self) -> None:
        """Refresh this group's lazy point-in-time gauges (term/role/lag/
        sessions) — the per-group half of the server's
        ``stats_snapshot``."""
        m = self.metrics
        m.gauge("raft_term").set(self.term)
        m.gauge("raft_is_leader").set(1 if self.role == LEADER else 0)
        m.gauge("raft_commit_index").set(self.commit_index)
        m.gauge("raft_last_applied").set(self.last_applied)
        m.gauge("raft_log_last_index").set(self.log.last_index)
        # commit lag: appended-but-uncommitted entries; apply lag:
        # committed-but-unapplied — both 0 in a healthy quiet cluster.
        m.gauge("raft_commit_lag").set(self.log.last_index
                                       - self.commit_index)
        m.gauge("raft_apply_lag").set(self.commit_index - self.last_applied)
        m.gauge("raft_members").set(len(self.members))
        live = 0
        queue_depth = 0
        for session in self.sessions.values():
            if session.state is SessionState.OPEN:
                live += 1
            queue_depth += len(session.event_queue)
        m.gauge("sessions_open").set(live)
        m.gauge("session_event_queue_depth").set(queue_depth)
        # snapshot plane (docs/DURABILITY.md): where the durable image
        # stands relative to the log, and whether any file was skipped
        # for a bad CRC since boot
        m.gauge("snap.last_snapshot_index").set(self._snap_index)
        m.gauge("snap.log_first_index").set(self.log.first_index)
        m.gauge("snap.enabled").set(
            1 if (self._snap_enabled and self._snapshots is not None) else 0)
        if self._snapshots is not None:
            m.gauge("snap.bad_crc_skipped").set(self._snapshots.bad_skipped)
