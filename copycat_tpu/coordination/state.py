"""Coordination state machines (reference ``LockState.java:33``,
``LeaderElectionState.java:31``, ``MembershipGroupState.java:33``,
``TopicState.java:31``, ``MessageBusState.java:30``)."""

from __future__ import annotations

from collections import OrderedDict, deque
from typing import Any

from ..io.serializer import serialize_with
from ..resource.state_machine import ResourceStateMachine
from ..server.state_machine import Commit
from . import commands as c


@serialize_with(117)
class LockState(ResourceStateMachine):
    """holder + FIFO wait queue + deterministic timeouts; grant delivered as a
    "lock" session event (reference ``LockState.java:41-66``).

    Capability fix over the reference (SURVEY.md §5.3): the lock IS released
    when the holder's session expires/closes — the reference version never
    re-queued it, wedging the lock forever on client crash."""

    def __init__(self) -> None:
        super().__init__()
        self._holder: Commit | None = None
        self._queue: deque[Commit] = deque()
        self._timers: dict[int, Any] = {}  # commit index -> timer

    def lock(self, commit: Commit[c.Lock]) -> int:
        # The command result is the waiter id (= commit index); every "lock"
        # event carries it so the client resolves the RIGHT waiter even when
        # timeouts fire out of FIFO order (a short try_lock queued behind an
        # unbounded lock can expire before the grant).
        if self._holder is None:
            self._holder = commit
            commit.session.publish("lock", {"id": commit.index, "acquired": True})
            return commit.index
        timeout = commit.operation.timeout
        if timeout == 0:
            commit.session.publish("lock", {"id": commit.index, "acquired": False})
            commit.clean()
            return commit.index
        self._queue.append(commit)
        if timeout and timeout > 0:
            def expire() -> None:
                self._timers.pop(commit.index, None)
                if commit in self._queue:
                    self._queue.remove(commit)
                    commit.session.publish(
                        "lock", {"id": commit.index, "acquired": False})
                    commit.clean()

            self._timers[commit.index] = self.executor.schedule(timeout, expire)
        return commit.index

    def unlock(self, commit: Commit[c.Unlock]) -> None:
        try:
            holder = self._holder
            if holder is None:
                return
            if holder.session.id != commit.session.id:
                raise ValueError("not the lock holder")
            holder.clean()
            self._grant_next()
        finally:
            commit.clean()

    def _grant_next(self) -> None:
        self._holder = None
        while self._queue:
            waiter = self._queue.popleft()
            timer = self._timers.pop(waiter.index, None)
            if timer is not None:
                timer.cancel()
            if waiter.session.is_open:
                self._holder = waiter
                waiter.session.publish("lock", {"id": waiter.index, "acquired": True})
                return
            waiter.clean()

    def close(self, session: Any) -> None:
        # Release on session death (fix over the reference).
        for waiter in [w for w in self._queue if w.session.id == session.id]:
            self._queue.remove(waiter)
            timer = self._timers.pop(waiter.index, None)
            if timer is not None:
                timer.cancel()
            waiter.clean()
        if self._holder is not None and self._holder.session.id == session.id:
            self._holder.clean()
            self._grant_next()

    def delete(self) -> None:
        for timer in self._timers.values():
            timer.cancel()
        self._timers.clear()
        for waiter in self._queue:
            waiter.clean()
        self._queue.clear()
        if self._holder is not None:
            self._holder.clean()
            self._holder = None


@serialize_with(113)
class LeaderElectionState(ResourceStateMachine):
    """leader + FIFO succession of listeners; "elect" event carries the epoch
    (= winning Listen's commit index — a fencing token)
    (reference ``LeaderElectionState.java:36-57,96``)."""

    def __init__(self) -> None:
        super().__init__()
        self._leader: Commit | None = None
        self._listeners: "OrderedDict[int, Commit]" = OrderedDict()  # session id -> Listen

    def listen(self, commit: Commit[c.ElectionListen]) -> None:
        if self._leader is None:
            self._leader = commit
            commit.session.publish("elect", commit.index)
        else:
            previous = self._listeners.get(commit.session.id)
            if previous is not None:
                previous.clean()
            self._listeners[commit.session.id] = commit

    def unlisten(self, commit: Commit[c.ElectionUnlisten]) -> None:
        try:
            session_id = commit.session.id
            waiting = self._listeners.pop(session_id, None)
            if waiting is not None:
                waiting.clean()
            elif self._leader is not None and self._leader.session.id == session_id:
                self._leader.clean()
                self._promote()
        finally:
            commit.clean()

    def is_leader(self, commit: Commit[c.ElectionIsLeader]) -> bool:
        try:
            return self._leader is not None and self._leader.index == commit.operation.epoch
        finally:
            commit.close()

    def _promote(self) -> None:
        self._leader = None
        while self._listeners:
            _, candidate = self._listeners.popitem(last=False)
            if candidate.session.is_open:
                self._leader = candidate
                candidate.session.publish("elect", candidate.index)
                return
            candidate.clean()

    def close(self, session: Any) -> None:
        # Leader failover on session death (reference close:36-49).
        waiting = self._listeners.pop(session.id, None)
        if waiting is not None:
            waiting.clean()
        if self._leader is not None and self._leader.session.id == session.id:
            self._leader.clean()
            self._promote()

    def delete(self) -> None:
        if self._leader is not None:
            self._leader.clean()
            self._leader = None
        for commit in self._listeners.values():
            commit.clean()
        self._listeners.clear()


@serialize_with(124)
class MembershipGroupState(ResourceStateMachine):
    """members keyed by instance-session id; join/leave fan-out events; remote
    execution routes (callback, args) to the target member's session
    (reference ``MembershipGroupState.java:33-95``)."""

    def __init__(self) -> None:
        super().__init__()
        self._members: dict[int, Commit] = {}  # session id -> Join commit
        self._timers: dict[int, Any] = {}

    def join(self, commit: Commit[c.GroupJoin]) -> list[int]:
        session_id = commit.session.id
        if session_id in self._members:
            commit.clean()
        else:
            for member in self._members.values():
                if member.session.is_open:
                    member.session.publish("join", session_id)
            self._members[session_id] = commit
        return list(self._members.keys())

    def leave(self, commit: Commit[c.GroupLeave]) -> None:
        try:
            self._remove_member(commit.session.id)
        finally:
            commit.clean()

    def members_list(self, commit: Commit[c.GroupListen]) -> list[int]:
        try:
            return list(self._members.keys())
        finally:
            commit.clean()

    def execute(self, commit: Commit[c.GroupExecute]) -> bool:
        try:
            op = commit.operation
            member = self._members.get(op.member)
            if member is None or not member.session.is_open:
                return False
            member.session.publish("execute", (op.callback, op.args))
            return True
        finally:
            commit.clean()

    def schedule(self, commit: Commit[c.GroupSchedule]) -> bool:
        op = commit.operation
        member = self._members.get(op.member)
        if member is None:
            commit.clean()
            return False

        def fire() -> None:
            self._timers.pop(commit.index, None)
            target = self._members.get(op.member)
            if target is not None and target.session.is_open:
                target.session.publish("execute", (op.callback, op.args))
            commit.clean()

        self._timers[commit.index] = (
            self.executor.schedule(op.delay or 0.0, fire), commit)
        return True

    def _remove_member(self, session_id: int) -> None:
        member = self._members.pop(session_id, None)
        if member is None:
            return
        member.clean()
        for other in self._members.values():
            if other.session.is_open:
                other.session.publish("leave", session_id)

    def close(self, session: Any) -> None:
        self._remove_member(session.id)

    def delete(self) -> None:
        for timer, pending in self._timers.values():
            timer.cancel()
            pending.clean()  # fire() will never run to clean it
        self._timers.clear()
        for member in self._members.values():
            member.clean()
        self._members.clear()


@serialize_with(128)
class TopicState(ResourceStateMachine):
    """Pub/sub through the log: listeners by session; publish fans out a
    "message" event, pruning closed sessions (reference ``TopicState.java:31``)."""

    def __init__(self) -> None:
        super().__init__()
        self._listeners: dict[int, Commit] = {}

    def listen(self, commit: Commit[c.TopicListen]) -> None:
        previous = self._listeners.get(commit.session.id)
        if previous is not None:
            previous.clean()
        self._listeners[commit.session.id] = commit

    def unlisten(self, commit: Commit[c.TopicUnlisten]) -> None:
        try:
            previous = self._listeners.pop(commit.session.id, None)
            if previous is not None:
                previous.clean()
        finally:
            commit.clean()

    def publish(self, commit: Commit[c.TopicPublish]) -> None:
        try:
            for session_id in list(self._listeners):
                listener = self._listeners[session_id]
                if listener.session.is_open:
                    listener.session.publish("message", commit.operation.message)
                else:
                    del self._listeners[session_id]
                    listener.clean()
        finally:
            commit.clean()

    def close(self, session: Any) -> None:
        listener = self._listeners.pop(session.id, None)
        if listener is not None:
            listener.clean()

    def delete(self) -> None:
        for commit in self._listeners.values():
            commit.clean()
        self._listeners.clear()


@serialize_with(129)
class MessageBusState(ResourceStateMachine):
    """Replicated registry for the out-of-band message bus: member addresses +
    topic consumers; register/unregister broadcast ConsumerInfo events
    (reference ``MessageBusState.java:30``)."""

    def __init__(self) -> None:
        super().__init__()
        self._members: dict[int, Commit] = {}  # session id -> BusJoin commit
        self._topics: dict[str, dict[int, Commit]] = {}  # topic -> session -> Register

    def join(self, commit: Commit[c.BusJoin]) -> dict:
        previous = self._members.get(commit.session.id)
        if previous is not None:
            previous.clean()  # re-join supersedes the old registration
        self._members[commit.session.id] = commit
        # Snapshot: topic -> list of consumer addresses (reference join returns
        # the full registry so a new bus can dial existing consumers).
        snapshot: dict[str, list] = {}
        for topic, registrations in self._topics.items():
            addresses = []
            for session_id in registrations:
                member = self._members.get(session_id)
                if member is not None:
                    addresses.append(member.operation.address)
            snapshot[topic] = addresses
        return snapshot

    def leave(self, commit: Commit[c.BusLeave]) -> None:
        try:
            self._remove(commit.session.id)
        finally:
            commit.clean()

    def register_consumer(self, commit: Commit[c.BusRegister]) -> None:
        topic = commit.operation.topic
        member = self._members.get(commit.session.id)
        if member is None:
            commit.clean()
            raise ValueError("join the bus before registering consumers")
        registrations = self._topics.setdefault(topic, {})
        previous = registrations.get(commit.session.id)
        registrations[commit.session.id] = commit
        if previous is not None:
            # Re-registration: clean the superseded commit and do NOT
            # re-broadcast (clients append addresses blindly).
            previous.clean()
            return
        info = c.ConsumerInfo(topic=topic, address=member.operation.address)
        for other in self._members.values():
            if other.session.is_open:
                other.session.publish("register", info)

    def unregister_consumer(self, commit: Commit[c.BusUnregister]) -> None:
        try:
            topic = commit.operation.topic
            registrations = self._topics.get(topic)
            if registrations is None:
                return
            registration = registrations.pop(commit.session.id, None)
            if registration is not None:
                registration.clean()
                member = self._members.get(commit.session.id)
                if member is not None:
                    info = c.ConsumerInfo(topic=topic, address=member.operation.address)
                    for other in self._members.values():
                        if other.session.is_open:
                            other.session.publish("unregister", info)
            if not registrations:
                self._topics.pop(topic, None)
        finally:
            commit.clean()

    def _remove(self, session_id: int) -> None:
        member = self._members.pop(session_id, None)
        for topic in list(self._topics):
            registrations = self._topics[topic]
            registration = registrations.pop(session_id, None)
            if registration is not None:
                registration.clean()
                if member is not None:
                    info = c.ConsumerInfo(topic=topic, address=member.operation.address)
                    for other in self._members.values():
                        if other.session.is_open:
                            other.session.publish("unregister", info)
            if not registrations:
                self._topics.pop(topic, None)
        if member is not None:
            member.clean()

    def close(self, session: Any) -> None:
        self._remove(session.id)

    def delete(self) -> None:
        for member in self._members.values():
            member.clean()
        self._members.clear()
        for registrations in self._topics.values():
            for commit in registrations.values():
                commit.clean()
        self._topics.clear()
