"""Distributed leader election (reference ``DistributedLeaderElection.java:66``).

``on_election(cb)`` — the first local listener submits Listen; the "elect"
event carries the EPOCH (= the winning Listen's commit index), which doubles
as a fencing token validated with ``is_leader(epoch)``."""

from __future__ import annotations

from typing import Any, Callable

from ..resource.resource import AbstractResource, resource_info
from ..utils.listeners import Listener, Listeners
from . import commands as c
from .state import LeaderElectionState


@resource_info(state_machine=LeaderElectionState)
class DistributedLeaderElection(AbstractResource):
    def __init__(self, client: Any) -> None:
        super().__init__(client)
        self._listeners = Listeners()
        self._listening = False
        self.session().on_event("elect", self._on_elect)

    def _on_elect(self, epoch: int) -> None:
        self._listeners.accept(epoch)

    async def on_election(self, callback: Callable[[int], Any]) -> Listener:
        """Register for leadership; ``callback(epoch)`` fires when this
        instance becomes leader."""
        listener = self._listeners.add(callback)
        if not self._listening:
            self._listening = True
            await self.submit(c.ElectionListen())
        return listener

    async def resign(self) -> None:
        """Give up leadership / candidacy (submits Unlisten)."""
        if self._listening:
            self._listening = False
            await self.submit(c.ElectionUnlisten())

    async def is_leader(self, epoch: int) -> bool:
        """Validate a fencing token against current leadership."""
        return bool(await self.submit(c.ElectionIsLeader(epoch=epoch)))
