"""Distributed leader election (reference ``DistributedLeaderElection.java:66``).

``on_election(cb)`` — the first local listener submits Listen; the "elect"
event carries the EPOCH (= the winning Listen's commit index), which doubles
as a fencing token validated with ``is_leader(epoch)``."""

from __future__ import annotations

import asyncio
from typing import Any, Callable

from ..resource.resource import AbstractResource, resource_info
from ..utils.listeners import Listener, Listeners
from . import commands as c
from .state import LeaderElectionState


@resource_info(state_machine=LeaderElectionState)
class DistributedLeaderElection(AbstractResource):
    def __init__(self, client: Any) -> None:
        super().__init__(client)
        self._listeners = Listeners()
        self._listening = False
        # Serializes Listen/Unlisten transitions: without it, an on_election
        # racing a resign() sees _listening still True mid-Unlisten and never
        # re-submits Listen (same gate as AbstractResource._tracked_listener).
        self._gate = asyncio.Lock()
        self.session().on_event("elect", self._on_elect)

    def _on_elect(self, epoch: int) -> None:
        self._listeners.accept(epoch)

    async def on_election(self, callback: Callable[[int], Any]) -> Listener:
        """Register for leadership; ``callback(epoch)`` fires when this
        instance becomes leader."""
        # The callback must be registered BEFORE the submit: with ATOMIC
        # consistency the "elect" event reaches us before the Listen response
        # (events-before-response, reference Consistency.java:157-176).
        listener = self._listeners.add(callback)
        try:
            async with self._gate:
                if not self._listening:
                    await self.submit(c.ElectionListen())
                    self._listening = True  # flips only on success
        except BaseException:
            listener.close()  # roll back so a retry re-submits
            raise
        return listener

    async def resign(self) -> None:
        """Give up leadership / candidacy (submits Unlisten)."""
        async with self._gate:
            if self._listening:
                await self.submit(c.ElectionUnlisten())
                self._listening = False

    async def is_leader(self, epoch: int) -> bool:
        """Validate a fencing token against current leadership."""
        return bool(await self.submit(c.ElectionIsLeader(epoch=epoch)))
