"""Group membership with remote execution (reference
``DistributedMembershipGroup.java:95``, ``GroupMember.java:31``).

Member id = the member's instance-session id.  Remote execution ships a
REGISTERED CALLBACK NAME + args through the log (the reference serialized
``Runnable`` closures — deliberately not reproduced; SURVEY.md §7.2 step 6):
the target member must have registered the name with ``handler()``."""

from __future__ import annotations

from typing import Any, Callable

from ..resource.resource import AbstractResource, resource_info
from ..utils.listeners import Listener, Listeners
from . import commands as c
from .state import MembershipGroupState


class GroupMember:
    """Handle for executing callbacks on a remote member."""

    def __init__(self, group: "DistributedMembershipGroup", member_id: int) -> None:
        self._group = group
        self.id = member_id

    async def execute(self, callback: str, *args: Any) -> bool:
        return bool(await self._group.submit(
            c.GroupExecute(member=self.id, callback=callback, args=list(args))))

    async def schedule(self, delay: float, callback: str, *args: Any) -> bool:
        return bool(await self._group.submit(
            c.GroupSchedule(member=self.id, delay=delay,
                            callback=callback, args=list(args))))


@resource_info(state_machine=MembershipGroupState)
class DistributedMembershipGroup(AbstractResource):
    def __init__(self, client: Any) -> None:
        super().__init__(client)
        self._join_listeners = Listeners()
        self._leave_listeners = Listeners()
        self._handlers: dict[str, Callable[..., Any]] = {}
        session = self.session()
        session.on_event("join", lambda m: self._join_listeners.accept(GroupMember(self, m)))
        session.on_event("leave", lambda m: self._leave_listeners.accept(m))
        session.on_event("execute", self._on_execute)

    def _on_execute(self, payload: Any) -> None:
        callback, args = payload
        handler = self._handlers.get(callback)
        if handler is not None:
            handler(*(args or []))

    def handler(self, name: str, fn: Callable[..., Any]) -> None:
        """Register a callback invocable by other members."""
        self._handlers[name] = fn

    async def join(self) -> GroupMember:
        """Join; this member's id is its instance-session id."""
        await self.submit(c.GroupJoin())
        return GroupMember(self, self.session().id)

    async def leave(self) -> None:
        await self.submit(c.GroupLeave())

    async def members(self) -> list[GroupMember]:
        ids = await self.submit(c.GroupListen())
        return [GroupMember(self, m) for m in ids]

    def member(self, member_id: int) -> GroupMember:
        return GroupMember(self, member_id)

    def on_join(self, callback: Callable[[GroupMember], Any]) -> Listener:
        return self._join_listeners.add(callback)

    def on_leave(self, callback: Callable[[int], Any]) -> Listener:
        return self._leave_listeners.add(callback)
