"""Pub/sub through the replicated log (reference ``DistributedTopic.java:61``).

``sync()`` = ATOMIC (subscribers receive before publish completes);
``async_()`` = SEQUENTIAL."""

from __future__ import annotations

from typing import Any, Callable

from ..resource.consistency import Consistency
from ..resource.resource import AbstractResource, resource_info
from ..utils.listeners import Listener, Listeners
from . import commands as c
from .state import TopicState


@resource_info(state_machine=TopicState)
class DistributedTopic(AbstractResource):
    def __init__(self, client: Any) -> None:
        super().__init__(client)
        self._subscribers = Listeners()
        self._listen_state: dict = {}
        self.session().on_event("message", self._on_message)

    def _on_message(self, message: Any) -> None:
        self._subscribers.accept(message)

    def sync(self) -> "DistributedTopic":
        """Publishes complete only after subscribers received the message."""
        return self.with_consistency(Consistency.ATOMIC)  # type: ignore[return-value]

    def async_(self) -> "DistributedTopic":
        """Publishes complete on commit; delivery is sequential, async."""
        return self.with_consistency(Consistency.SEQUENTIAL)  # type: ignore[return-value]

    async def publish(self, message: Any) -> None:
        await self.submit(c.TopicPublish(message=message))

    async def subscribe(self, callback: Callable[[Any], Any]) -> Listener:
        return await self._tracked_listener(
            self._subscribers, callback, self._listen_state,
            c.TopicListen(), c.TopicUnlisten)
