"""Distributed mutex (reference ``DistributedLock.java:58``).

The grant is delivered as a session EVENT, not the command response: the
client registers a waiter future and completes it when the matching "lock"
event arrives. Events carry the waiter id (the Lock commit's index, also the
command result) so out-of-FIFO timeout events resolve the RIGHT waiter."""

from __future__ import annotations

import asyncio
from typing import Any

from ..resource.resource import AbstractResource, resource_info
from ..utils.tasks import spawn
from . import commands as c
from .state import LockState


@resource_info(state_machine=LockState)
class DistributedLock(AbstractResource):
    def __init__(self, client: Any) -> None:
        super().__init__(client)
        self._waiters: dict[int, asyncio.Future] = {}
        # Grants can arrive BEFORE the submit response that tells us our id
        # (events-before-response for LINEARIZABLE commands): buffer them.
        self._early_events: dict[int, bool] = {}
        # Submits that failed after the server may have committed them: their
        # grant (if any) will arrive under an id we never learned.
        self._orphaned = 0
        self._inflight = 0
        self.session().on_event("lock", self._on_lock_event)

    def _on_lock_event(self, event: dict) -> None:
        waiter_id, acquired = int(event["id"]), bool(event["acquired"])
        fut = self._waiters.pop(waiter_id, None)
        if fut is None:
            self._early_events[waiter_id] = acquired
            self._reap_orphans()
        elif not fut.done():
            fut.set_result(acquired)
        elif acquired:
            # Grant landed on an abandoned waiter (lock() task cancelled while
            # awaiting): release immediately so other clients can proceed.
            spawn(self.submit(c.Unlock()))

    def _reap_orphans(self) -> None:
        """Discard buffered events belonging to failed submits (releasing any
        grant among them). Only safe when no submit is in flight — then every
        buffered event is provably unclaimable."""
        while self._orphaned and not self._inflight and self._early_events:
            _, acquired = self._early_events.popitem()
            self._orphaned -= 1
            if acquired:
                spawn(self.submit(c.Unlock()))

    async def _submit_lock(self, timeout: float) -> asyncio.Future:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._inflight += 1
        try:
            waiter_id = int(await self.submit(c.Lock(timeout=timeout)))
        except BaseException:
            self._inflight -= 1
            self._orphaned += 1
            self._reap_orphans()
            raise
        self._inflight -= 1
        if waiter_id in self._early_events:
            fut.set_result(self._early_events.pop(waiter_id))
        else:
            self._waiters[waiter_id] = fut
        self._reap_orphans()
        return fut

    async def lock(self) -> None:
        """Acquire, waiting as long as it takes."""
        fut = await self._submit_lock(-1)
        acquired = await fut
        assert acquired, "unbounded lock() resolved False"

    async def try_lock(self, timeout: float | None = None) -> bool:
        """Immediate attempt (timeout=None/0) or bounded wait (timeout>0).
        Timeouts are measured in replicated log time: they may fire later than
        wall clock, never earlier (reference tryLock Javadoc)."""
        fut = await self._submit_lock(timeout or 0)
        return await fut

    async def unlock(self) -> None:
        await self.submit(c.Unlock())
