"""Distributed mutex (reference ``DistributedLock.java:58``).

The grant is delivered as a session EVENT, not the command response: the
client registers a waiter future and completes it when the matching "lock"
event arrives. Events carry the waiter id (the Lock commit's index, also the
command result) so out-of-FIFO timeout events resolve the RIGHT waiter."""

from __future__ import annotations

import asyncio
from typing import Any

from ..resource.resource import AbstractResource, resource_info
from . import commands as c
from .state import LockState


@resource_info(state_machine=LockState)
class DistributedLock(AbstractResource):
    def __init__(self, client: Any) -> None:
        super().__init__(client)
        self._waiters: dict[int, asyncio.Future] = {}
        # Grants can arrive BEFORE the submit response that tells us our id
        # (events-before-response for LINEARIZABLE commands): buffer them.
        self._early_events: dict[int, bool] = {}
        self.session().on_event("lock", self._on_lock_event)

    def _on_lock_event(self, event: dict) -> None:
        waiter_id, acquired = int(event["id"]), bool(event["acquired"])
        fut = self._waiters.pop(waiter_id, None)
        if fut is not None:
            if not fut.done():
                fut.set_result(acquired)
        else:
            self._early_events[waiter_id] = acquired

    async def _submit_lock(self, timeout: float) -> asyncio.Future:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        waiter_id = int(await self.submit(c.Lock(timeout=timeout)))
        if waiter_id in self._early_events:
            fut.set_result(self._early_events.pop(waiter_id))
        else:
            self._waiters[waiter_id] = fut
        return fut

    async def lock(self) -> None:
        """Acquire, waiting as long as it takes."""
        fut = await self._submit_lock(-1)
        acquired = await fut
        assert acquired, "unbounded lock() resolved False"

    async def try_lock(self, timeout: float | None = None) -> bool:
        """Immediate attempt (timeout=None/0) or bounded wait (timeout>0).
        Timeouts are measured in replicated log time: they may fire later than
        wall clock, never earlier (reference tryLock Javadoc)."""
        fut = await self._submit_lock(timeout or 0)
        return await fut

    async def unlock(self) -> None:
        await self.submit(c.Unlock())
