"""Distributed mutex (reference ``DistributedLock.java:58``).

The grant is delivered as a session EVENT, not the command response: the
client queues a waiter future and completes it when the "lock" event arrives
(in FIFO order matching the server queue)."""

from __future__ import annotations

import asyncio
from collections import deque
from typing import Any

from ..resource.resource import AbstractResource, resource_info
from . import commands as c
from .state import LockState


@resource_info(state_machine=LockState)
class DistributedLock(AbstractResource):
    def __init__(self, client: Any) -> None:
        super().__init__(client)
        self._waiters: deque[asyncio.Future] = deque()
        self.session().on_event("lock", self._on_lock_event)

    def _on_lock_event(self, acquired: bool) -> None:
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(bool(acquired))
                return

    async def _submit_lock(self, timeout: float) -> asyncio.Future:
        """Queue a waiter and submit; on submit failure the waiter is removed
        so a later grant cannot resolve a stale future out of order."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiters.append(fut)
        try:
            await self.submit(c.Lock(timeout=timeout))
        except BaseException:
            if fut in self._waiters:
                self._waiters.remove(fut)
            raise
        return fut

    async def lock(self) -> None:
        """Acquire, waiting as long as it takes."""
        fut = await self._submit_lock(-1)
        acquired = await fut
        assert acquired, "unbounded lock() resolved False"

    async def try_lock(self, timeout: float | None = None) -> bool:
        """Immediate attempt (timeout=None/0) or bounded wait (timeout>0).
        Timeouts are measured in replicated log time: they may fire later than
        wall clock, never earlier (reference tryLock Javadoc)."""
        fut = await self._submit_lock(timeout or 0)
        return await fut

    async def unlock(self) -> None:
        await self.submit(c.Unlock())
