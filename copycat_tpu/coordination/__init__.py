"""Coordination resources (reference ``coordination/`` module, SURVEY.md §2.1):
lock, leader election, membership group, topic (log pub/sub), message bus
(direct node-to-node messaging with a log-replicated registry)."""

from .lock import DistributedLock
from .election import DistributedLeaderElection
from .group import DistributedMembershipGroup, GroupMember
from .topic import DistributedTopic
from .bus import DistributedMessageBus, Message, MessageConsumer, MessageProducer
from .state import (
    LeaderElectionState,
    LockState,
    MembershipGroupState,
    MessageBusState,
    TopicState,
)

__all__ = [
    "DistributedLock",
    "DistributedLeaderElection",
    "DistributedMembershipGroup",
    "GroupMember",
    "DistributedTopic",
    "DistributedMessageBus",
    "Message",
    "MessageProducer",
    "MessageConsumer",
    "LockState",
    "LeaderElectionState",
    "MembershipGroupState",
    "TopicState",
    "MessageBusState",
]
