"""Coordination operation catalogs.

Serializer id blocks per the reference (SURVEY.md §2.1): message bus 85-89
(``MessageBusCommands.java``), leader election 110-112
(``LeaderElectionCommands``), lock 115-116 (``LockCommands.java``), membership
group 120-123 (``MembershipGroupCommands.java``), topic 125-127
(``TopicCommands.java``).

Deliberate change from the reference: group remote execution ships a
REGISTERED CALLBACK NAME + args instead of a serialized closure
(``MembershipGroupCommands.java:85`` logs ``Runnable`` objects — a misfeature;
see SURVEY.md §7.2 step 6).
"""

from __future__ import annotations

from ..io.serializer import serialize_with
from ..protocol.messages import Message as _M
from ..protocol.operations import Command, CommandConsistency, Persistence, Query


class Tombstone(_M, Command):
    def persistence(self) -> Persistence:
        return Persistence.PERSISTENT


# -- message bus (85-89) ----------------------------------------------------


@serialize_with(85)
class BusJoin(_M, Command):
    _fields = ("address",)


@serialize_with(86)
class BusLeave(Tombstone):
    _fields = ()


@serialize_with(87)
class BusRegister(_M, Command):
    _fields = ("topic",)


@serialize_with(88)
class BusUnregister(Tombstone):
    _fields = ("topic",)


@serialize_with(89)
class ConsumerInfo(_M):
    """Event payload: a consumer's (topic, address) (``MessageBusCommands``)."""

    _fields = ("topic", "address")


# -- leader election (110-112) ----------------------------------------------


@serialize_with(110)
class ElectionListen(_M, Command):
    def consistency(self) -> CommandConsistency:
        return CommandConsistency.LINEARIZABLE

    _fields = ()


@serialize_with(111)
class ElectionUnlisten(Tombstone):
    def consistency(self) -> CommandConsistency:
        return CommandConsistency.LINEARIZABLE

    _fields = ()


@serialize_with(112)
class ElectionIsLeader(_M, Query):
    """Fencing-token validation: is `epoch` still the current leadership?"""

    _fields = ("epoch",)


# -- lock (115-116) ----------------------------------------------------------


@serialize_with(115)
class Lock(_M, Command):
    # timeout: <0 wait forever, 0 immediate try, >0 queued with deadline.
    _fields = ("timeout",)

    def consistency(self) -> CommandConsistency:
        return CommandConsistency.LINEARIZABLE


@serialize_with(116)
class Unlock(Tombstone):
    _fields = ()

    def consistency(self) -> CommandConsistency:
        return CommandConsistency.LINEARIZABLE


# -- membership group (120-123) ---------------------------------------------


@serialize_with(120)
class GroupJoin(_M, Command):
    _fields = ()


@serialize_with(121)
class GroupLeave(Tombstone):
    _fields = ()


@serialize_with(122)
class GroupListen(_M, Command):
    _fields = ()


@serialize_with(123)
class GroupSchedule(_M, Command):
    """Remote execution on a member: (member id, delay, callback name, args)."""

    _fields = ("member", "delay", "callback", "args")


@serialize_with(119)
class GroupExecute(_M, Command):
    _fields = ("member", "callback", "args")


# -- topic (125-127) ---------------------------------------------------------


@serialize_with(125)
class TopicListen(_M, Command):
    _fields = ()


@serialize_with(126)
class TopicUnlisten(Tombstone):
    _fields = ()


@serialize_with(127)
class TopicPublish(_M, Command):
    _fields = ("message",)
