"""Direct node-to-node message bus (reference ``DistributedMessageBus.java:74``).

Only MEMBERSHIP and the consumer REGISTRY go through the Raft log; message
payloads travel over DIRECT transport connections between buses (the
reference dials raw Catalyst connections; here the same Transport SPI).  In
the TPU design this is the host-side DCN path (SURVEY.md §5.8).
"""

from __future__ import annotations

import asyncio
import itertools
from typing import Any, Callable

from ..io.serializer import serialize_with
from ..io.transport import Address, Connection, Transport, TransportError
from ..protocol.messages import Message as _WireMessage
from ..resource.resource import AbstractResource, resource_info
from . import commands as c
from .state import MessageBusState


@serialize_with(108)
class Message(_WireMessage):
    """(topic, body) value type (reference ``Message.java:30``)."""

    _fields = ("topic", "body")

    def __init__(self, topic: str = "", body: Any = None) -> None:
        self.topic = topic
        self.body = body


class MessageProducer:
    """Round-robins messages over the topic's consumer addresses."""

    def __init__(self, bus: "DistributedMessageBus", topic: str) -> None:
        self._bus = bus
        self.topic = topic
        self._rr = itertools.count()

    async def send(self, body: Any) -> Any:
        addresses = self._bus._consumers.get(self.topic)
        if not addresses:
            raise TransportError(f"no consumers for topic '{self.topic}'")
        address = addresses[next(self._rr) % len(addresses)]
        connection = await self._bus._connection_to(address)
        return await connection.send(Message(self.topic, body))

    async def close(self) -> None:
        pass


class MessageConsumer:
    """A registered handler for one topic on this bus node."""

    def __init__(self, bus: "DistributedMessageBus", topic: str,
                 handler: Callable[[Any], Any]) -> None:
        self._bus = bus
        self.topic = topic
        self.handler = handler

    async def close(self) -> None:
        await self._bus._unregister_consumer(self)


@resource_info(state_machine=MessageBusState)
class DistributedMessageBus(AbstractResource):
    def __init__(self, client: Any) -> None:
        super().__init__(client)
        self._transport: Transport | None = None
        self._server = None
        self._address: Address | None = None
        self._consumers: dict[str, list[Address]] = {}  # replicated registry view
        self._local_consumers: dict[str, MessageConsumer] = {}
        self._connections: dict[Address, Connection] = {}
        session = self.session()
        session.on_event("register", self._on_register)
        session.on_event("unregister", self._on_unregister)

    # -- registry events ---------------------------------------------------

    def _on_register(self, info: c.ConsumerInfo) -> None:
        self._consumers.setdefault(info.topic, []).append(info.address)

    def _on_unregister(self, info: c.ConsumerInfo) -> None:
        addresses = self._consumers.get(info.topic)
        if addresses and info.address in addresses:
            addresses.remove(info.address)
            if not addresses:
                del self._consumers[info.topic]

    # -- lifecycle ---------------------------------------------------------

    async def open(self, address: Address, transport: Transport) -> "DistributedMessageBus":
        """Start this bus node: listen for direct connections + join the
        replicated registry (reference ``open(Address)``)."""
        self._transport = transport
        self._address = address
        self._server = transport.server()
        await self._server.listen(address, self._accept)
        snapshot = await self.submit(c.BusJoin(address=address))
        for topic, addresses in (snapshot or {}).items():
            self._consumers.setdefault(topic, []).extend(addresses)
        return self

    async def close_bus(self) -> None:
        await self.submit(c.BusLeave())
        for connection in list(self._connections.values()):
            await connection.close()
        self._connections.clear()
        if self._server is not None:
            await self._server.close()
            self._server = None

    def _accept(self, connection: Connection) -> None:
        connection.handler(Message, self._on_message)

    async def _on_message(self, message: Message) -> Any:
        consumer = self._local_consumers.get(message.topic)
        if consumer is None:
            raise TransportError(f"no consumer for topic '{message.topic}'")
        result = consumer.handler(message.body)
        if asyncio.iscoroutine(result):
            result = await result
        return result

    # -- producers/consumers ----------------------------------------------

    async def producer(self, topic: str) -> MessageProducer:
        return MessageProducer(self, topic)

    async def consumer(self, topic: str, handler: Callable[[Any], Any]) -> MessageConsumer:
        if self._address is None:
            raise RuntimeError("open(address, transport) the bus first")
        consumer = MessageConsumer(self, topic, handler)
        self._local_consumers[topic] = consumer
        await self.submit(c.BusRegister(topic=topic))
        return consumer

    async def _unregister_consumer(self, consumer: MessageConsumer) -> None:
        if self._local_consumers.get(consumer.topic) is consumer:
            del self._local_consumers[consumer.topic]
            await self.submit(c.BusUnregister(topic=consumer.topic))

    # -- direct connections ------------------------------------------------

    async def _connection_to(self, address: Address) -> Connection:
        connection = self._connections.get(address)
        if connection is not None and not connection.closed:
            return connection
        assert self._transport is not None
        connection = await self._transport.client().connect(address)
        self._connections[address] = connection
        return connection
