"""ResourceManager — THE multiplexer (reference ``ResourceManager.java:35``).

One replicated state machine hosting every resource:

- ``keys``: name -> resource id (= the creating commit's log index,
  ``ResourceManager.java:160``)
- ``resources``: resource id -> (state machine, per-resource executor)
- ``instances``: instance id -> (resource, virtual session, owner session)

Instance ops are routed to the owning resource's executor with the commit
re-parented onto the resource's virtual session (``operateResource:56``).
Session expiry/close fans out to every resource the session touched
(``ResourceManager.java:238-266``).
"""

from __future__ import annotations

import logging
import zlib
from typing import Any, Callable

from ..server.session import ServerSession, SessionState
from ..server.state_machine import Commit, StateMachine, StateMachineExecutor
from ..utils.metrics import MetricsRegistry
from ..resource.operations import ResourceCommand, ResourceQuery
from ..resource.state_machine import ResourceStateMachine, ResourceStateMachineExecutor
from .operations import (
    CreateResource,
    DeleteResource,
    GetResource,
    InstanceCommand,
    InstanceEvent,
    InstanceOperation,
    InstanceQuery,
    ResourceExists,
)


class ManagedResourceSession:
    """Per-(resource-instance) virtual session bound to a client session
    (reference ``ManagedResourceSession.java:38``): same lifecycle as the
    parent, but events are wrapped in InstanceEvent for client-side routing."""

    def __init__(self, instance_id: int, parent: ServerSession) -> None:
        self.id = instance_id
        self.parent = parent

    @property
    def state(self) -> SessionState:
        return self.parent.state

    @property
    def is_open(self) -> bool:
        return self.parent.is_open

    @property
    def timeout(self) -> float:
        return self.parent.timeout

    def publish(self, event: str, message: Any = None) -> None:
        self.parent.publish(event, InstanceEvent(self.id, message))

    def __repr__(self) -> str:
        return f"ManagedResourceSession(instance={self.id}, client={self.parent.id})"


class ManagerResourceExecutor(ResourceStateMachineExecutor):
    """Per-resource executor: own callback map and logger, timers tracked for
    cancel-on-delete (reference ``ResourceManagerStateMachineExecutor.java:43``)."""

    def __init__(self, parent: StateMachineExecutor, resource_id: int, name: str) -> None:
        super().__init__(parent)
        self._context_logger = logging.getLogger(f"{name}-{resource_id}")
        self._tracked: set[Any] = set()

    def logger(self) -> logging.Logger:
        return self._context_logger

    def schedule(self, delay: float, callback: Callable[[], None], interval=None):
        # One-shot timers untrack themselves on fire so a steady TTL workload
        # doesn't pin every fired timer (+ its commit closure) until delete.
        holder: dict[str, Any] = {}

        def wrapped() -> None:
            try:
                callback()
            finally:
                if interval is None and "timer" in holder:
                    self._tracked.discard(holder["timer"])

        timer = super().schedule(delay, wrapped, interval)
        holder["timer"] = timer
        self._tracked.add(timer)
        return timer

    def close(self) -> None:
        for timer in self._tracked:
            timer.cancel()
        self._tracked.clear()


class ResourceHolder:
    __slots__ = ("resource_id", "key", "state_machine", "executor",
                 "machine_cls")

    def __init__(self, resource_id: int, key: str,
                 state_machine: ResourceStateMachine,
                 executor: ManagerResourceExecutor,
                 machine_cls: type | None = None) -> None:
        self.resource_id = resource_id
        self.key = key
        self.state_machine = state_machine
        self.executor = executor
        # The LOGICAL machine class requested at create time — the actual
        # instance may be its device-backed equivalent when the manager
        # runs the TPU executor (device_executor.device_machine_for).
        self.machine_cls = machine_cls or type(state_machine)


class InstanceHolder:
    __slots__ = ("instance_id", "resource", "session", "owner")

    def __init__(self, instance_id: int, resource: ResourceHolder,
                 session: ManagedResourceSession, owner: ServerSession) -> None:
        self.instance_id = instance_id
        self.resource = resource
        self.session = session
        self.owner = owner


class _ReparentedCommit(Commit):
    """Commit view with the session swapped for the resource's virtual session
    (reference ``ResourceManagerCommit.java:31``)."""

    __slots__ = ("_parent",)

    def __init__(self, parent: Commit, session: ManagedResourceSession, operation: Any):
        super().__init__(parent.index, session, parent.time, operation, None)
        self._parent = parent

    def clean(self) -> None:
        self._parent.clean()

    def close(self) -> None:
        self._parent.close()


class ResourceManager(StateMachine):
    """The single top-level state machine wired into every server.

    ``executor="tpu"`` routes the fixed-shape resource types
    (value/long, map, set, queue, lock, leader election) to the in-process
    device engine — one device Raft group per resource — with the CPU
    state machines as the default and the automatic fallback for
    unsupported types and engine exhaustion (SURVEY.md §7.1; selection
    seam mirrors ``AtomixReplica.java:374``). The executor choice must be
    uniform across the cluster, like ``withStateMachine`` in the reference.
    """

    def __init__(self, executor: str = "cpu",
                 engine_config: Any | None = None,
                 group_id: int = 0, num_groups: int = 1,
                 engine: Any = None) -> None:
        super().__init__()
        if executor not in ("cpu", "tpu"):
            raise ValueError(f"unknown executor {executor!r}")
        self.keys: dict[str, int] = {}
        self.resources: dict[int, ResourceHolder] = {}
        self.instances: dict[int, InstanceHolder] = {}
        self.executor_kind = executor
        # Keyspace sharding (docs/SHARDING.md): on a multi-group server
        # each group hosts its own manager; resource/instance ids are
        # stamped ``index * num_groups + group_id`` so ids are globally
        # unique AND self-routing (``id % num_groups`` = owning group).
        # With num_groups == 1 the stamp is the identity — ids (and the
        # whole manager) are bit-identical to the unsharded plane.
        self.group_id = group_id
        self.num_groups = max(1, num_groups)
        # ``engine`` shares ONE DeviceEngine across the per-group
        # managers: every group's device-backed resources live in rows
        # of the same [G×P] tensor plane and compile once.
        self._engine: Any = engine
        self._engine_config = engine_config
        # Catalog counters feed inline; point-in-time gauges refresh in
        # stats() (the server's stats_snapshot pulls it — see
        # docs/OBSERVABILITY.md).
        self.metrics = MetricsRegistry()

    @classmethod
    def route_group(cls, operation: Any, groups: int) -> int:
        """Hash routing over the keyspace (docs/SHARDING.md): catalog
        ops route by a stable CRC of the resource key; instance ops are
        self-routing (ids carry their group residue). Deterministic
        across members, restarts, and processes — the stability contract
        tests/test_sharding.py pins."""
        t = type(operation)
        if t in (InstanceCommand, InstanceQuery):
            return operation.resource % groups
        if t is DeleteResource:
            return operation.instance_id % groups
        key = getattr(operation, "key", None)
        if isinstance(key, str):  # GetResource / CreateResource / Exists
            return zlib.crc32(key.encode()) % groups
        return 0

    @property
    def device_engine(self) -> Any:
        if self._engine is None and self.executor_kind == "tpu":
            from .device_executor import DeviceEngine
            self._engine = DeviceEngine(self._engine_config)
        return self._engine

    def prewarm(self) -> None:
        """Build + jit-compile the device engine up front (called at server
        open, before any client session exists — the first compile can take
        tens of seconds and must not stall keep-alives mid-session)."""
        if self.executor_kind == "tpu":
            self.device_engine._ensure()

    def begin_window(self) -> Any:
        """Open a shared device round pump for one apply batch (``None``
        on the CPU executor). The applying server defers device-backed
        handler chains into it so a batch of committed entries shares
        engine rounds instead of paying submit→commit→settle per op."""
        if self.executor_kind != "tpu":
            return None
        return self.device_engine.begin_window()

    # -- catalog ops -------------------------------------------------------

    def get_resource(self, commit: Commit[GetResource]) -> int:
        op = commit.operation
        holder = self._get_or_create_resource(commit, op.key, op.state_machine)
        # At most one instance per (resource, client session) for get()
        # (reference getResource:77-146).
        for instance in self.instances.values():
            if instance.resource is holder and instance.owner is commit.session:
                commit.clean()
                return instance.instance_id
        return self._create_instance(commit, holder).instance_id

    def create_resource(self, commit: Commit[CreateResource]) -> int:
        op = commit.operation
        holder = self._get_or_create_resource(commit, op.key, op.state_machine)
        return self._create_instance(commit, holder).instance_id

    def resource_exists(self, commit: Commit[ResourceExists]) -> bool:
        try:
            return commit.operation.key in self.keys
        finally:
            commit.close()

    def delete_resource(self, commit: Commit[DeleteResource]) -> bool:
        try:
            instance = self.instances.get(commit.operation.instance_id)
            if instance is None:
                return False
            holder = instance.resource
            holder.executor.close()
            try:
                holder.state_machine.delete()
            except Exception:
                logging.getLogger(__name__).exception("resource delete failed")
            self.keys.pop(holder.key, None)
            self.resources.pop(holder.resource_id, None)
            for iid in [i for i, h in self.instances.items() if h.resource is holder]:
                del self.instances[iid]
            self.metrics.counter("resources_deleted").inc()
            return True
        finally:
            commit.clean()

    # -- instance op routing ----------------------------------------------

    def instance_command(self, commit: Commit[InstanceCommand]) -> Any:
        return self._operate(commit)

    def instance_query(self, commit: Commit[InstanceQuery]) -> Any:
        return self._operate(commit)

    def _operate(self, commit: Commit) -> Any:
        op: InstanceOperation = commit.operation
        instance = self.instances.get(op.resource)
        if instance is None:
            commit.clean()
            raise ValueError(f"unknown resource instance {op.resource}")
        reparented = _ReparentedCommit(commit, instance.session, op.operation)
        return instance.resource.executor.execute(reparented)

    # -- batched server-side pump (vector lane) ---------------------------

    def vector_route(self, operation: Any):
        """Classify one committed operation for the applying server's
        vector lane: ``(machine, instance, inner_op, spec)`` when the op
        is a routed resource command whose device-backed machine can
        express it as ONE device op (``DeviceBackedStateMachine.
        vector_spec``), else ``None`` — the per-entry windowed apply
        handles everything else. Exact-type checks keep subclasses (which
        may override semantics) on the general path."""
        if type(operation) is not InstanceCommand:
            return None
        envelope = operation.operation
        if type(envelope) is not ResourceCommand:
            return None
        instance = self.instances.get(operation.resource)
        if instance is None:
            return None
        machine = instance.resource.state_machine
        spec_fn = getattr(machine, "vector_spec", None)
        if spec_fn is None:
            return None
        inner = envelope.operation
        spec = spec_fn(inner)
        if spec is None:
            return None
        return machine, instance, inner, spec

    def apply_key(self, operation: Any):
        """Dependency key for the applying server's parallel-apply
        classifier (docs/SHARDING.md "Apply ordering"): the catalog
        RESOURCE an operation mutates — stable resource id, identical on
        every member (``index * num_groups + group_id`` stamping) — or
        ``None`` when the footprint is not a single live resource
        (catalog create/get/delete, unknown instances): the conservative
        whole-window barrier. Instances of one key share a resource (and
        its device group), so two instances of the same map collide on
        the same key — exactly the FIFO the classifier must preserve."""
        if type(operation) is not InstanceCommand:
            return None
        instance = self.instances.get(operation.resource)
        if instance is None:
            return None
        return instance.resource.resource_id

    # -- batched read pump (query vector lane) -----------------------------

    def query_route(self, operation: Any):
        """Classify one READ for the applying server's read window:
        ``(machine, instance, inner_op, spec)`` when the op is a routed
        resource query whose device-backed machine can serve it as ONE
        device query (``DeviceBackedStateMachine.query_spec``), else
        ``None`` — the per-op query lane handles everything else
        (catalog queries, host-shadowed state, CPU machines). Exact-type
        checks keep subclasses on the general path, like
        :meth:`vector_route`."""
        if type(operation) is not InstanceQuery:
            return None
        envelope = operation.operation
        if type(envelope) is not ResourceQuery:
            return None
        instance = self.instances.get(operation.resource)
        if instance is None:
            return None
        machine = instance.resource.state_machine
        spec_fn = getattr(machine, "query_spec", None)
        if spec_fn is None:
            return None
        inner = envelope.operation
        spec = spec_fn(inner)
        if spec is None:
            return None
        return machine, instance, inner, spec

    # -- edge read tier (docs/EDGE_READS.md) -------------------------------

    def edge_locate(self, operation: Any):
        """``(resource_id, instance_id)`` when ``operation`` is a routed
        resource read of a live instance — the subscription handle the
        edge tier registers under (deltas are keyed by the RESOURCE the
        apply path mutates, :meth:`apply_key`; the client addresses its
        replica by the instance id it queries through). ``None``
        otherwise. Exact-type checks keep subclasses on the server
        path, like :meth:`query_route`."""
        if type(operation) is not InstanceQuery:
            return None
        if type(operation.operation) is not ResourceQuery:
            return None
        instance = self.instances.get(operation.resource)
        if instance is None:
            return None
        return instance.resource.resource_id, operation.resource

    def edge_state_of(self, resource_id: int) -> Any:
        """Tagged edge state of one resource (the machine's
        ``edge_state`` hook): ``NotImplemented`` when the machine never
        serves edge reads, ``None`` when the resource is gone — the
        subscriber's replica entry must retire."""
        holder = self.resources.get(resource_id)
        if holder is None:
            return None
        return holder.state_machine.edge_state()

    # -- internals ---------------------------------------------------------

    def _get_or_create_resource(self, commit: Commit, key: str,
                                machine_cls: type) -> ResourceHolder:
        resource_id = self.keys.get(key)
        if resource_id is not None:
            holder = self.resources[resource_id]
            if holder.machine_cls is not machine_cls:
                commit.clean()
                raise ValueError(
                    f"resource '{key}' exists with type "
                    f"{holder.machine_cls.__name__}, not {machine_cls.__name__}")
            return holder
        resource_id = commit.index * self.num_groups + self.group_id
        self.keys[key] = resource_id
        machine = self._instantiate_machine(machine_cls)
        executor = ManagerResourceExecutor(self.executor, resource_id, key)
        machine.init(executor)
        holder = ResourceHolder(resource_id, key, machine, executor,
                                machine_cls=machine_cls)
        self.resources[resource_id] = holder
        self.metrics.counter("resources_created").inc()
        return holder

    def _instantiate_machine(self, machine_cls: type) -> ResourceStateMachine:
        """CPU machine by default; its device-backed equivalent when the
        TPU executor is selected, the type is device-eligible, and the
        engine still has a free group (fallback otherwise)."""
        if self.executor_kind == "tpu":
            from .device_executor import device_machine_for
            device_cls = device_machine_for(
                machine_cls, self.device_engine.config.resource)
            if device_cls is not None:
                group = self.device_engine.allocate()
                if group is not None:
                    return device_cls(self.device_engine, group)
        return machine_cls()

    def _create_instance(self, commit: Commit, holder: ResourceHolder) -> InstanceHolder:
        instance_id = commit.index * self.num_groups + self.group_id
        session = ManagedResourceSession(instance_id, commit.session)
        instance = InstanceHolder(instance_id, holder, session, commit.session)
        self.instances[instance_id] = instance
        holder.state_machine.register(session)
        return instance

    # -- snapshot hooks (crash-recovery plane, docs/DURABILITY.md) ---------

    def snapshot_state(self) -> Any:
        """Serialize the whole resource catalog + machine state.

        Device-backed machines need no per-machine serialization: ALL of
        their replicated state lives in the engine's ``RaftGroups``
        pytree, captured wholesale through ``models/checkpoint.py``'s
        field-path ``.npz`` format (one blob for every device resource).
        CPU machines participate through their own
        ``snapshot_state``/``restore_state`` hooks; a live CPU machine
        WITHOUT hooks makes the whole manager opt out (returns
        ``NotImplemented``) — the server then stays on the replay-only
        recovery path rather than persist a lossy image.
        """
        resources = []
        for rid, holder in self.resources.items():
            machine = holder.state_machine
            state = machine.snapshot_state()
            if state is NotImplemented:
                logging.getLogger(__name__).info(
                    "resource %r (%s) cannot snapshot; manager stays "
                    "on replay-only recovery", holder.key,
                    type(machine).__name__)
                return NotImplemented
            resources.append({
                "id": rid, "key": holder.key, "cls": holder.machine_cls,
                "group": getattr(machine, "_group", None), "state": state})
        instances = [
            {"id": iid, "resource": inst.resource.resource_id,
             "owner": inst.owner.id}
            for iid, inst in self.instances.items()]
        engine_blob = None
        next_group = 0
        free: list[int] = []
        if self._engine is not None and self._engine._groups is not None:
            from ..models import checkpoint
            engine_blob = checkpoint.save_bytes(self._engine._groups)
            next_group = self._engine._next_group
            free = sorted(self._engine._free)
        return {"keys": dict(self.keys), "resources": resources,
                "instances": instances, "engine": engine_blob,
                "engine_next_group": next_group, "engine_free": free}

    def restore_state(self, data: Any, sessions: dict) -> None:
        # build the whole catalog into locals FIRST: a failure partway
        # (bad blob, machine restore raising) leaves this manager's live
        # dicts untouched, so the server's full-replay fallback starts
        # from pristine state instead of a half-restored catalog
        engine_restored = False
        if data["engine"] is not None and self.executor_kind == "tpu":
            self.device_engine.restore_snapshot(
                data["engine"], data["engine_next_group"],
                data["engine_free"])
            engine_restored = True
        resources: dict[int, ResourceHolder] = {}
        try:
            for rec in data["resources"]:
                machine_cls = rec["cls"]
                if rec["group"] is not None and self.executor_kind == "tpu":
                    from .device_executor import device_machine_for
                    device_cls = device_machine_for(
                        machine_cls, self.device_engine.config.resource)
                    machine = device_cls(self.device_engine, rec["group"])
                else:
                    machine = machine_cls()
                executor = ManagerResourceExecutor(
                    self.executor, rec["id"], rec["key"])
                machine.init(executor)
                machine.restore_state(rec["state"], sessions)
                resources[rec["id"]] = ResourceHolder(
                    rec["id"], rec["key"], machine, executor,
                    machine_cls=machine_cls)
        except Exception:
            if engine_restored:
                # the full-replay fallback re-applies history from index
                # 1; it must not land on snapshot-state device groups —
                # drop the restored RaftGroups so the next _ensure()
                # builds fresh
                eng = self._engine
                eng._groups = None
                eng._next_group = 0
                eng._free = []
            raise
        instances: dict[int, InstanceHolder] = {}
        for rec in data["instances"]:
            owner = sessions.get(rec["owner"])
            holder = resources.get(rec["resource"])
            if owner is None or holder is None:
                continue  # the owning session died with the snapshot
            session = ManagedResourceSession(rec["id"], owner)
            instances[rec["id"]] = InstanceHolder(
                rec["id"], holder, session, owner)
            # re-register so machines that track sessions re-bind them
            # (device machines re-attach listeners from device state)
            holder.state_machine.register(session)
        self.keys = dict(data["keys"])
        self.resources = resources
        self.instances = instances

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """Catalog stats for the server's ``stats_snapshot()``: resource
        and instance gauges, create/delete counters, device-engine group
        occupancy when the TPU executor is live."""
        m = self.metrics
        m.gauge("resources").set(len(self.resources))
        m.gauge("instances").set(len(self.instances))
        device_backed = sum(
            1 for h in self.resources.values()
            if getattr(h.state_machine, "_group", None) is not None)
        m.gauge("resources_device_backed").set(device_backed)
        if self._engine is not None:
            groups_used = getattr(self._engine, "_next_group", None)
            if groups_used is not None:
                m.gauge("device_groups_used").set(int(groups_used))
        out = m.snapshot()
        out["executor"] = self.executor_kind
        # device-plane flight-recorder telemetry (models/telemetry.py):
        # the engine's device.* family + invariant-monitor summary ride
        # the manager section of /stats when telemetry is live
        groups = getattr(self._engine, "_groups", None)
        hub = getattr(groups, "telemetry", None)
        if hub is not None:
            out["device"] = hub.snapshot()
            out["device"]["invariants"] = hub.monitor.summary()
        return out

    # -- session lifecycle fan-out (SURVEY.md §3.4) ------------------------

    def expire(self, session: ServerSession) -> None:
        for instance in list(self.instances.values()):
            if instance.owner is session:
                instance.resource.state_machine.expire(instance.session)

    def close(self, session: ServerSession) -> None:
        for iid, instance in list(self.instances.items()):
            if instance.owner is session:
                instance.resource.state_machine.close(instance.session)
                del self.instances[iid]
