"""The TPU executor behind the Atomix SPI.

SURVEY.md §7.1: "the TPU executor selectable at replica build time (mirror
of ``withStateMachine(new ResourceManager())`` at ``AtomixReplica.java:374``)".
A replica/server built with ``executor="tpu"`` routes ``get``/``create`` of
the fixed-shape resource types to the batched device engine — one device
Raft group per resource instance, catalog unchanged in the
:class:`~copycat_tpu.manager.state.ResourceManager` — while every other
type (and device-pool overflow / non-int32 payloads) transparently stays on
the CPU state machines. Same public resource API either way.

Architecture (two replication planes, one state machine discipline):

- The CPU Raft log linearizes client ops ACROSS SERVER PROCESSES and owns
  sessions, durability and compaction — exactly as for CPU resources.
- Each server applies committed ops to its own in-process
  :class:`DeviceEngine` (a ``RaftGroups`` batch — the flagship vectorized
  consensus+apply program). Replica convergence across servers follows
  from determinism: the engine's visible resource state is a pure function
  of the committed device-op sequence, which is identical on every server
  because it is derived from the shared CPU log in apply order.

Determinism rules the device-backed machines must (and do) observe:

1. Device ops never carry device-clock TTLs (``c``/deadline args are 0 or
   sentinel): TTLs and lock timeouts run through the HOST'S replicated
   log-time timers (``StateMachineExecutor.schedule`` — SURVEY.md §5.9),
   so device resource state is independent of how many device rounds each
   server happened to step.
2. Queries never append device log entries (no escalation): the device
   log stays ``[election NoOp] + committed commands`` on every server, so
   log indexes — used as election fencing epochs — agree everywhere.
3. Commits are retained host-side exactly like the CPU machines retain
   them (``_Held`` discipline): the CPU log's compaction contract is
   preserved; the device holds the *data plane*.

Reference obligations: resource routing ``ResourceManager.java:56``,
executor selection ``AtomixReplica.java:374``, state machine semantics
``AtomicValueState.java:32``, ``MapState.java:32``, ``SetState.java:32``,
``QueueState.java:30``, ``LockState.java:33``, ``LeaderElectionState.java:31``.
"""

from __future__ import annotations

import inspect
import logging
from collections import deque

from typing import Any, NamedTuple

from ..resource.state_machine import ResourceStateMachine
from ..server.state_machine import Commit
from ..atomic import commands as vc
from ..collections import commands as cc
from ..coordination import commands as oc

logger = logging.getLogger(__name__)

INT32_MIN = -(2 ** 31)
INT32_MAX = 2 ** 31 - 1


def _devint(v: Any) -> bool:
    """True if ``v`` can live in a device int32 lane.

    ``bool`` is excluded (a device round-trip would turn ``True`` into
    ``1`` — a visible type change vs the CPU path), as is the engine's
    INT_MIN FAIL sentinel.
    """
    return (isinstance(v, int) and not isinstance(v, bool)
            and INT32_MIN < v <= INT32_MAX)


class DeviceEngineConfig(NamedTuple):
    """Shape of the per-server device batch (uniform across the cluster —
    the engine replicates deterministically only if every server runs the
    same shapes, like ``withStateMachine`` must be uniform in the
    reference)."""

    capacity: int = 1024      # device groups = max device-backed resources
    num_peers: int = 3
    log_slots: int = 64
    submit_slots: int = 4
    seed: int = 0             # shared PRNG seed — same election history
    # Optional jax.sharding.Mesh: shard the engine's group axis across
    # this server's local devices (parallel/mesh.py specs — zero
    # cross-device collectives, census-verified). A LOCAL placement
    # choice only: sharding never changes the integer state evolution,
    # so servers with different meshes (or none) still replicate
    # deterministically; the uniformity requirement above is about
    # shapes, not placement. The mesh's 'groups' axis size must divide
    # capacity (each shard holds capacity/shards groups).
    mesh: Any = None
    # Optional ops.apply.ResourceConfig: which device pools this engine
    # compiles in. Pool state is carried through every engine round, so a
    # deployment that hosts only counters can provision
    # ``ResourceConfig.counters_only()`` and nearly halve the round
    # (measured 9.3 -> 5.1 ms at capacity 1024 on CPU). Resource types
    # whose pool is compiled out (size 0) transparently fall back to the
    # CPU state machines — same public API, same semantics, no device
    # acceleration (``device_machine_for`` consults this). Must be
    # uniform across the cluster, like every other engine shape. None =
    # all pools at their defaults (previous behavior).
    resource: Any = None
    # Device-plane flight-recorder telemetry (models/telemetry.py):
    # compiles the per-group telemetry block into the engine step and
    # surfaces device.* metrics + /flight on the stats listener. Pure
    # output — never changes the engine's state evolution, so it may
    # differ across servers (a local observability choice, not a shape).
    # COPYCAT_TELEMETRY=1 / COPYCAT_INVARIANTS also enable it per-env.
    telemetry: bool = False


class _Job:
    """One device-op chain (a handler or timer generator) inside a window."""

    __slots__ = ("group", "gen", "settle", "ctx", "on_done", "tag",
                 "resume_round", "pending", "done", "result", "exc")

    def __init__(self, group: int | None, gen: Any, settle: bool,
                 ctx: Any = None, on_done: Any = None) -> None:
        self.group = group
        self.gen = gen
        self.settle = settle
        self.ctx = ctx
        self.on_done = on_done
        self.tag: int | None = None
        self.resume_round: int | None = None
        self.pending: int | None = None
        self.done = False
        self.result: Any = None
        self.exc: BaseException | None = None


class DeviceJob:
    """A device-backed handler's suspended execution.

    Device command handlers are generator functions — each device op is a
    ``yield`` — so the applying server can BATCH many handlers' chains into
    shared engine rounds (:class:`DeviceWindow`) instead of paying
    submit→commit→settle per op (the round-3 SPI bottleneck). A caller
    with no window drives the chain alone via :meth:`run`.
    """

    __slots__ = ("engine", "group", "settle", "gen")
    is_device_job = True  # duck-typing marker for the applying server

    def __init__(self, engine: "DeviceEngine", group: int, settle: bool,
                 gen: Any) -> None:
        self.engine = engine
        self.group = group
        self.settle = settle
        self.gen = gen

    def run(self) -> Any:
        return self.engine.run_now(self.group, self.gen, self.settle)


class DeviceWindow:
    """Shared round pump for one apply batch.

    Jobs added in CPU-log order are driven concurrently ACROSS device
    groups and strictly FIFO WITHIN a group: a group's next job starts
    only when its predecessor finished, so each group's device-op sequence
    is the concatenation of complete per-handler chains in log order —
    identical on every server regardless of how commit batches were cut
    (the determinism requirement of the two-plane design above). One
    engine round serves every group's current outstanding op, so a batch
    of K independent handlers costs ~max-chain-length rounds, not
    sum-of-chains.

    Finalization callbacks (response futures, event seal/push) run in add
    order — the reference's per-session program-order completion.
    """

    MAX_ROUNDS = 2000

    def __init__(self, engine: "DeviceEngine") -> None:
        self._eng = engine
        self._active: dict[int, _Job] = {}          # group -> running job
        self._waiting: dict[int, deque[_Job]] = {}  # group -> queued jobs
        self._order: list[_Job] = []                # finalization order
        self._finalized = 0
        #: device ops yielded but not yet submitted: (job, op, a, b, c).
        #: Submission is deferred so one vectorized ``submit_batch`` per
        #: pump cycle replaces a per-op ``submit`` (the per-op deque +
        #: dict staging was a top line of the SPI burst profile).
        self._staged: list = []
        #: per-entry context inherited by timer-spawned jobs (the applying
        #: server sets it around each command entry's tick+execute)
        self.job_ctx: Any = None

    @property
    def busy(self) -> bool:
        return bool(self._active) or self._finalized < len(self._order)

    # -- enqueue -----------------------------------------------------------

    def add_job(self, job: DeviceJob, ctx: Any = None,
                on_done: Any = None) -> None:
        """Defer a handler chain; ``on_done(result, exc)`` runs at its
        log-ordered finalization slot."""
        self._enqueue(_Job(job.group, job.gen, job.settle, ctx, on_done))

    def add_ready(self, on_done: Any) -> None:
        """Defer an already-computed completion so it finalizes in log
        order behind pending device jobs (no-op ordering shim when the
        window is idle)."""
        j = _Job(None, None, False, None, on_done)
        j.done = True
        self._order.append(j)
        self._try_finalize()

    def _enqueue(self, j: _Job) -> None:
        self._order.append(j)
        if j.group in self._active:
            self._waiting.setdefault(j.group, deque()).append(j)
        else:
            self._active[j.group] = j
            self._advance(j, None)
        self._try_finalize()

    # -- drive -------------------------------------------------------------

    def _advance(self, job: _Job, value: Any) -> None:
        """Resume ``job`` with ``value`` until it suspends on a device op
        or finishes; iteratively promote waiting jobs of freed groups (a
        long chain of no-op jobs must not recurse)."""
        work: list[tuple[_Job, Any]] = [(job, value)]
        while work:
            j, val = work.pop()
            try:
                if j.ctx is not None:
                    with j.ctx:
                        yielded = j.gen.send(val)
                else:
                    yielded = j.gen.send(val)
            except StopIteration as stop:
                j.done = True
                j.result = stop.value
            except BaseException as e:  # noqa: BLE001 — surfaced at finalize
                j.done = True
                j.exc = e
            if not j.done:
                if yielded[0] == "cmd":
                    # defer the engine submit: _flush_staged turns every
                    # op staged this cycle into ONE vectorized
                    # submit_batch call (tags assigned there)
                    self._staged.append((j, yielded[1], yielded[2],
                                         yielded[3], yielded[4]))
                    j.resume_round = None
                    continue
                # unknown yield: fail THIS job (still freeing its group
                # below so queued jobs keep running)
                j.done = True
                j.exc = RuntimeError(f"unknown device yield {yielded!r}")
                j.gen.close()
            del self._active[j.group]
            q = self._waiting.get(j.group)
            if q:
                nxt = q.popleft()
                if not q:
                    del self._waiting[j.group]
                self._active[j.group] = nxt
                work.append((nxt, None))

    def _collect(self, groups) -> bool:
        """Resolve finished tags / elapsed settle windows; returns whether
        any job progressed (False → the pump must step a round)."""
        progressed = False
        now = groups.rounds
        results = groups.results
        for j in list(self._active.values()):
            if j.tag is not None and j.tag in results:
                res = results.pop(j.tag)
                j.tag = None
                if j.settle:
                    # event consumers (lock/election) resume only after
                    # their op's session events drained to the host buffer
                    j.pending = res
                    j.resume_round = now + self._eng.SETTLE_ROUNDS
                else:
                    progressed = True
                    self._advance(j, res)
            elif (j.tag is None and j.resume_round is not None
                  and now >= j.resume_round):
                j.resume_round = None
                progressed = True
                self._advance(j, j.pending)
        return progressed

    def _flush_staged(self, groups) -> None:
        """Submit every staged device op in ONE vectorized call (tags
        assigned here); per-group FIFO holds because submit_batch's
        stable group sort preserves staging order within a group."""
        staged, self._staged = self._staged, []
        if not staged:
            return
        if len(staged) == 1:
            j, op, a, b, c = staged[0]
            j.tag = groups.submit(j.group, op, a, b, c)
            return
        tags = groups.submit_batch(
            [s[0].group for s in staged], [s[1] for s in staged],
            [s[2] for s in staged], [s[3] for s in staged],
            [s[4] for s in staged])
        for s, t in zip(staged, tags.tolist()):
            s[0].tag = t

    def pump(self) -> None:
        """Drive every pending job to completion, then run finalizations
        in add order."""
        if self._active:
            groups = self._eng._ensure()
            self._flush_staged(groups)
            start = groups.rounds
            while self._active:
                if groups.rounds - start > self.MAX_ROUNDS:
                    raise TimeoutError(
                        f"device window stuck after {self.MAX_ROUNDS} rounds"
                        f" without progress (groups {sorted(self._active)})")
                if self._collect(groups):
                    # a no-progress watchdog, not a total budget: a long
                    # FIFO chain on one group is legitimate work
                    start = groups.rounds
                    self._flush_staged(groups)
                elif self._active:
                    # When every active job is sitting out a KNOWN settle
                    # window (event consumers after their op committed),
                    # fuse exactly that many rounds into one compiled
                    # program + fetch — one tunnel round-trip instead of
                    # min(waits). A fresh submit needs no fusion: the
                    # step commits and reports in-round under full
                    # delivery (commit latency 1), so the loaded round
                    # resolves it.
                    waits = [j.resume_round - groups.rounds
                             for j in self._active.values()
                             if j.resume_round is not None]
                    if (len(waits) == len(self._active)
                            and min(waits) > 1):
                        groups.step_rounds(min(waits))
                    else:
                        groups.step_round()
        self._try_finalize()

    barrier = pump  # drain point before entries that read manager state

    def close(self) -> None:
        try:
            self.pump()
        finally:
            if self._eng._window is self:
                self._eng._window = None

    def _try_finalize(self) -> None:
        while self._finalized < len(self._order):
            j = self._order[self._finalized]
            if not j.done:
                break
            self._finalized += 1
            if j.on_done is not None:
                j.on_done(j.result, j.exc)
            elif j.exc is not None:
                # timer-spawned chain failed; mirror executor.tick's policy
                logger.exception("device timer chain failed", exc_info=j.exc)


class DeviceEngine:
    """In-process device batch shared by all device-backed resources of one
    server; allocates one group per resource instance.

    Freed groups ARE reused: every device-backed machine resets its
    device-resident state (clear/cancel/release commands) in ``delete()``
    before releasing its group, so a recycled group starts clean. Reuse is
    not just thrift — it makes the device-vs-CPU placement decision a
    function of the LIVE device-resource count only, which is identical
    between a full history and a compacted replay (compaction only drops
    create/delete pairs, preserving the live set at every retained log
    position); a monotonic allocator would instead diverge after restart.
    When all groups are live, allocation returns ``None`` and the manager
    falls back to the CPU state machine for that resource.
    """

    #: extra rounds stepped after a command before an event-consuming
    #: machine (lock/election) resumes, so session events emitted by the
    #: apply are drained into the host buffer first — a fixed,
    #: deterministic settle budget (events surface one round after the
    #: emitting apply).
    SETTLE_ROUNDS = 2

    def __init__(self, config: DeviceEngineConfig | None = None) -> None:
        self.config = config or DeviceEngineConfig()
        self._groups = None          # built lazily: first device resource
        self._next_group = 0
        self._free: list[int] = []   # released (reset) groups, lowest first
        self._window: DeviceWindow | None = None

    # -- lifecycle ---------------------------------------------------------

    def _ensure(self):
        if self._groups is None:
            from ..models.raft_groups import RaftGroups
            from ..utils.platform import enable_compilation_cache
            enable_compilation_cache()  # restarts skip the jit stall
            cfg = self.config
            if cfg.mesh is not None:
                shards = cfg.mesh.shape.get("groups", 1)
                if cfg.capacity % shards:
                    raise ValueError(
                        f"DeviceEngineConfig.capacity={cfg.capacity} not "
                        f"divisible by the mesh 'groups' axis ({shards})")
                peer_shards = cfg.mesh.shape.get("peers", 1)
                if cfg.num_peers % peer_shards:
                    # Without this, the failure surfaces later as an
                    # opaque XLA sharding error inside device_put.
                    raise ValueError(
                        f"DeviceEngineConfig.num_peers={cfg.num_peers} not "
                        f"divisible by the mesh 'peers' axis ({peer_shards})")
            from ..ops.consensus import Config
            engine_cfg = None
            if cfg.resource is not None or cfg.telemetry:
                engine_cfg = Config(
                    telemetry=cfg.telemetry,
                    **({"resource": cfg.resource}
                       if cfg.resource is not None else {}))
            self._groups = RaftGroups(
                cfg.capacity, cfg.num_peers, log_slots=cfg.log_slots,
                submit_slots=cfg.submit_slots, seed=cfg.seed,
                mesh=cfg.mesh, config=engine_cfg)
            # Warm-up: deterministic election rounds (fixed seed). After
            # this, full delivery keeps every leader stable, so queries are
            # always servable without stepping.
            #
            # COST (measured, round 4): elections settle in ≤~15 rounds
            # at any capacity (max_rounds=200 is a bound, not the cost);
            # wall time is dominated by the one-time jit compile — ~8-9 s
            # on CPU at capacity 16/256/1024 alike, tens of seconds for a
            # first-ever TPU compile (then persistently cached). Servers
            # built through AtomixServer/AtomixReplica pay it at OPEN
            # (ResourceManager.prewarm), before any client session
            # exists — never as a hidden stall inside the first
            # create()'s apply.
            self._groups.wait_for_leaders(max_rounds=200)
        return self._groups

    def allocate(self) -> int | None:
        """Lowest free device group, or ``None`` when all are live."""
        if self._free:
            self._ensure()
            import heapq
            return heapq.heappop(self._free)
        if self._next_group >= self.config.capacity:
            return None
        self._ensure()
        group = self._next_group
        self._next_group += 1
        return group

    def release(self, group: int) -> None:
        """Return a group to the pool. The caller (the machine's
        ``delete()``) must have reset the group's device state first."""
        import heapq
        heapq.heappush(self._free, group)

    def restore_snapshot(self, blob: bytes, next_group: int,
                         free: list[int]) -> None:
        """Rebuild the engine's ``RaftGroups`` from a server-plane
        snapshot (``models/checkpoint.py`` field-path bytes) plus the
        group-allocator bookkeeping captured with it — the device half of
        the crash-recovery plane (docs/DURABILITY.md)."""
        from ..models import checkpoint
        self._groups = checkpoint.load_bytes(blob, mesh=self.config.mesh)
        self._next_group = int(next_group)
        self._free = sorted(int(g) for g in free)

    # -- op plane ----------------------------------------------------------

    def begin_window(self) -> DeviceWindow:
        """Open the shared round pump for one apply batch (the applying
        server closes it after the batch's last entry)."""
        window = DeviceWindow(self)
        self._window = window
        return window

    @property
    def window(self) -> DeviceWindow | None:
        return self._window

    def run_now(self, group: int, gen: Any, settle: bool = False) -> Any:
        """Drive one chain to completion on a private pump (the per-op
        path for callers outside any window)."""
        w = DeviceWindow(self)
        job = _Job(group, gen, settle)
        w._enqueue(job)
        w.pump()
        if job.exc is not None:
            raise job.exc
        return job.result

    def run_excl(self, group: int, gen: Any, settle: bool = False) -> Any:
        """Drain the open window (if any), then drive ``gen`` alone — for
        delete/session-close chains that must observe fully-applied state
        and complete before the caller proceeds (e.g. group release must
        precede any later allocate)."""
        if self._window is not None and self._window.busy:
            self._window.barrier()
        return self.run_now(group, gen, settle)

    def spawn(self, group: int, gen: Any, settle: bool = False) -> None:
        """Timer-fired device work.

        During a COMMAND entry's tick (``window.job_ctx`` set) the chain
        joins the window at its log-ordered slot — before the entry's own
        handler job — under the entry's context, so its publishes seal
        with that entry. Outside a command entry (non-command entries
        barrier the window first; or no window at all) it runs
        immediately: the window is empty then, so immediate execution IS
        the log-ordered slot, and publishes land in the live touched set
        the current entry seals."""
        if self._window is not None and self._window.job_ctx is not None:
            self._window._enqueue(
                _Job(group, gen, settle, self._window.job_ctx, None))
        else:
            self.run_now(group, gen, settle)

    def command(self, group: int, opcode: int, a: int = 0, b: int = 0,
                c: int = 0) -> int:
        """Submit one committed device op and return its applied result
        (standalone per-op path; handlers go through generator chains)."""
        def one():
            return (yield ("cmd", int(opcode), int(a), int(b), int(c)))

        return self.run_now(group, one(), settle=True)

    def query(self, group: int, opcode: int, a: int = 0, b: int = 0,
              c: int = 0) -> int:
        """Read-only op served from the leader lane's applied state.

        Never appends to the device log (determinism rule #2) —
        ``RaftGroups.serve_query`` is the non-escalating lane; after the
        warm-up election the leader is stable and has applied everything
        it committed, so it serves without stepping.
        """
        return self._ensure().serve_query(group, opcode, a, b, c)

    def take_events(self, group: int, cursor: int) -> tuple[list, int]:
        """Events for ``group`` with seq > cursor; returns (events, cursor)."""
        if self._groups is None:
            return [], cursor
        out = []
        for ev in self._groups.events.get(group, []):
            if ev[0] > cursor:
                out.append(ev)
                cursor = ev[0]
        return out, cursor

    def event_cursor(self, group: int) -> int:
        """Current newest event seq for ``group`` (start-of-life cursor)."""
        if self._groups is None:
            return -1
        evs = self._groups.events.get(group, [])
        return evs[-1][0] if evs else -1

    def run_vector(self, groups_idx, opcodes, a, b, c,
                   max_rounds: int = 200) -> list[int]:
        """The batched server-side pump's device leg: stage EVERY row in
        one vectorized pass (the ``_stage_direct`` fast lane scatters a
        fitting burst straight into the next round's Submits) and step
        shared engine rounds until all rows committed — under full
        delivery the loaded round accepts, replicates, commits and
        reports in ONE round, so a 1k-op batch costs one engine round
        instead of 1k generator chains through the window machinery.
        Returns raw results aligned with the input rows. Per-group FIFO
        holds because the staging's stable group sort preserves row
        order within a group and the engine applies slots in log order.

        The primary lane is :meth:`RaftGroups.drive_vector` (untracked
        tags, output-array correlation — no per-op dict bookkeeping);
        when direct staging is refused (queued ops from generator
        chains, held groups) it degrades to the tracked submit_batch +
        results-dict walk, which interleaves correctly with the queue-
        managed machinery."""
        groups = self._ensure()
        res = groups.drive_vector(groups_idx, opcodes, a, b, c,
                                  max_rounds=max_rounds)
        if res is not None:
            return res.tolist()
        tags = groups.submit_batch(groups_idx, opcodes, a, b, c)
        tag_l = tags.tolist()
        results = groups.results
        for _ in range(max_rounds):
            groups.step_round()
            if all(t in results for t in tag_l):
                return [results.pop(t) for t in tag_l]
        missing = sum(1 for t in tag_l if t not in results)
        raise TimeoutError(
            f"vector pump: {missing}/{len(tag_l)} rows uncommitted after "
            f"{max_rounds} rounds")

    def run_query_vector(self, groups_idx, opcodes, a, b, c) -> list[int]:
        """The batched READ pump's device leg: evaluate every row through
        ONE :func:`~copycat_tpu.ops.consensus.query_step` engine round
        (``RaftGroups.drive_query_vector``) instead of a blocking
        ``serve_query`` device round-trip per read. No log append, no
        state change — serving is leader-applied-state only, exactly the
        per-op :meth:`query` lane's semantics."""
        groups = self._ensure()
        return groups.drive_query_vector(
            groups_idx, opcodes, a, b, c).tolist()


class _Held:
    """Retained commit + optional host-side value + TTL timer.

    Mirrors the CPU machines' retained-commit discipline
    (``collections/state.py``): the commit is cleaned exactly when its
    effect is superseded, keeping CPU-log compaction correct while the
    value itself lives on device (``on_device=True``) or host-side
    (shadow overflow / non-int32 payloads).
    """

    __slots__ = ("commit", "value", "on_device", "timer")

    def __init__(self, commit: Commit, value: Any = None,
                 on_device: bool = False):
        self.commit = commit
        self.value = value
        self.on_device = on_device
        self.timer = None

    def discard(self) -> None:
        if self.timer is not None:
            self.timer.cancel()
            self.timer = None
        self.commit.clean()


# Vector-op finalize kinds (vector_spec's last element): how the host
# bookkeeping consumes the device result at the batched pump's finalize.
VK_CAS, VK_GET_AND_SET, VK_SET = 1, 2, 3

# Query-spec finalize kinds (query_spec's last element). Reads never
# mutate host bookkeeping, so the only consumption modes are the raw
# device int and its truthiness.
QK_RAW, QK_BOOL = 1, 2


class DeviceBackedStateMachine(ResourceStateMachine):
    """Base for state machines whose data plane is a device group.

    Command handlers (and every helper that issues device ops) are
    GENERATOR functions: ``result = yield from self._cmd(...)``. ``init``
    wraps each registered generator handler so the applying server
    receives a :class:`DeviceJob` it can batch into the open
    :class:`DeviceWindow` — the shared round pump — instead of a value.
    Query handlers stay plain functions (they never append device ops —
    determinism rule #2) and serve synchronously. Host-state-only command
    handlers (e.g. value ``listen``) still run as jobs (``yield from ()``)
    so their host mutations keep log order relative to in-flight chains.
    """

    #: True for machines that consume device session events (lock grants,
    #: election promotions): their chains resume only after each op's
    #: events settle into the host buffer.
    SETTLES = False

    def __init__(self, engine: DeviceEngine, group: int) -> None:
        super().__init__()
        self._eng = engine
        self._group = group
        # skip events addressed to a predecessor resource of this group
        self._ev_cursor = engine.event_cursor(group)

    def init(self, executor) -> None:
        super().init(executor)
        executor.rewrap(self._wrap_handler)

    def _wrap_handler(self, fn):
        inner = getattr(fn, "__func__", fn)
        if not inspect.isgeneratorfunction(inner):
            return fn

        def wrapped(commit, _fn=fn):
            return DeviceJob(self._eng, self._group, type(self).SETTLES,
                             _fn(commit))

        return wrapped

    def _cmd(self, opcode: int, a: int = 0, b: int = 0, c: int = 0):
        """Issue one device command from inside a chain:
        ``result = yield from self._cmd(...)``."""
        result = yield ("cmd", int(opcode), int(a), int(b), int(c))
        return result

    def _spawn(self, gen) -> None:
        """Hand a timer-fired device chain to the engine (window-ordered)."""
        self._eng.spawn(self._group, gen, type(self).SETTLES)

    def _run_excl(self, gen):
        """Drive a chain to completion now (delete/session-close hooks)."""
        return self._eng.run_excl(self._group, gen, type(self).SETTLES)

    def _qry(self, opcode: int, a: int = 0, b: int = 0, c: int = 0) -> int:
        return self._eng.query(self._group, opcode, a, b, c)

    def _events(self) -> list:
        evs, self._ev_cursor = self._eng.take_events(
            self._group, self._ev_cursor)
        return evs

    # -- batched server-side pump (vector lane) ---------------------------
    #
    # A machine that can express an operation as ONE device op with no
    # host side effects beyond simple result bookkeeping opts into the
    # applying server's vector lane: ``vector_spec`` classifies the op at
    # stage time (None = take the generator slow path), ``vector_finalize``
    # consumes the device result in log order. The pair must be
    # bit-identical in visible state evolution to the generator handler —
    # tests/test_spi_vector_pump.py proves it differentially.

    def vector_spec(self, operation: Any
                    ) -> tuple[int, int, int, int, int] | None:
        """(opcode, a, b, c, finalize_kind) for a vector-eligible op, or
        ``None`` when the op needs its generator handler (host shadow,
        TTLs, listeners, events, multi-op chains)."""
        return None

    def vector_finalize(self, kind: int, operation: Any, raw: int,
                        commit: Commit) -> Any:
        raise NotImplementedError  # pragma: no cover — spec implies finalize

    # -- batched read pump (query vector lane) -----------------------------
    #
    # The read-side analog of vector_spec/vector_finalize: a machine
    # whose query handler is exactly ONE device query (no host shadow, no
    # host-only answer) opts its reads into the applying server's read
    # window, which evaluates the whole window through one query_step
    # engine round. The pair must return exactly what the plain query
    # handler returns — tests/test_spi_read_pump.py proves it
    # differentially against the per-op lane.

    def query_spec(self, operation: Any
                   ) -> tuple[int, int, int, int, int] | None:
        """(opcode, a, b, c, finalize_kind) for a read servable as ONE
        device query, or ``None`` when the read needs its handler (host
        shadow values, host-derived answers, mixed host/device state)."""
        return None

    def query_finalize(self, kind: int, operation: Any, raw: int) -> Any:
        """Shape the raw device int like the plain handler's return."""
        return bool(raw) if kind == QK_BOOL else raw

    def delete(self) -> None:
        self._eng.release(self._group)


# ---------------------------------------------------------------------------
# value / long
# ---------------------------------------------------------------------------

class DeviceAtomicValueState(DeviceBackedStateMachine):
    """Linearizable register: int32 values live in the device register;
    ``None``/non-int32 payloads shadow host-side (semantics identical to
    ``AtomicValueState`` — reference ``AtomicValueState.java:32``)."""

    _UNSET = object()

    def __init__(self, engine: DeviceEngine, group: int) -> None:
        super().__init__(engine, group)
        self._held: _Held | None = None      # None = register unset
        self._shadow: Any = self._UNSET      # host value when not on device
        self._listeners: dict[int, Commit] = {}
        self._timer = None

    # -- current value -----------------------------------------------------

    def _value(self) -> Any:
        if self._held is None:
            return None
        if self._held.on_device:
            return self._qry(ops().OP_VALUE_GET)
        return self._held.value

    def _set_current(self, commit: Commit, value: Any, ttl: float | None):
        """Install ``value``; returns the previous value. One device
        command at most (GET_AND_SET covers the device→device case)."""
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        was_device = self._held is not None and self._held.on_device
        if self._held is not None:
            previous_host = None if was_device else self._held.value
            self._held.discard()
        else:
            previous_host = None
        if _devint(value):
            previous_dev = yield from self._cmd(
                ops().OP_VALUE_GET_AND_SET, value)
            previous = previous_dev if was_device else previous_host
            self._held = _Held(commit, on_device=True)
        else:
            if was_device:
                previous = yield from self._cmd(ops().OP_VALUE_GET_AND_SET, 0)
            else:
                previous = previous_host
            self._held = _Held(commit, value=value)
        if ttl:
            self._arm_ttl(ttl)
        return previous

    def _arm_ttl(self, ttl: float) -> None:
        held = self._held

        def expire() -> None:  # fires at log time; the chain drives ordered
            def chain():
                if self._held is held:
                    yield from self._clear_value()
                    self._publish_change(None)

            self._spawn(chain())

        self._timer = self.executor.schedule(ttl, expire)

    def _clear_value(self):
        if self._held is not None:
            if self._held.on_device:
                yield from self._cmd(ops().OP_VALUE_SET, 0)
            self._held.discard()
            self._held = None
        self._timer = None

    # -- handlers ----------------------------------------------------------

    def get(self, commit: Commit[vc.Get]) -> Any:
        try:
            return self._value()
        finally:
            commit.close()

    def set(self, commit: Commit[vc.Set]) -> None:
        op = commit.operation
        previous = yield from self._set_current(commit, op.value, op.ttl)
        if previous != op.value:
            self._publish_change(op.value)

    def get_and_set(self, commit: Commit[vc.GetAndSet]) -> Any:
        op = commit.operation
        previous = yield from self._set_current(commit, op.value, op.ttl)
        if previous != op.value:
            self._publish_change(op.value)
        return previous

    def compare_and_set(self, commit: Commit[vc.CompareAndSet]) -> bool:
        op = commit.operation
        if (self._held is not None and self._held.on_device
                and _devint(op.expect) and _devint(op.update)):
            # single device CAS — the hot path (BASELINE config #1)
            if (yield from self._cmd(ops().OP_VALUE_CAS, op.expect,
                                     op.update)):
                self._held.discard()
                self._held = _Held(commit, on_device=True)
                self._reschedule_ttl(op.ttl)
                if op.update != op.expect:
                    self._publish_change(op.update)
                return True
            commit.clean()
            return False
        if self._value() == op.expect:
            yield from self._set_current(commit, op.update, op.ttl)
            if op.update != op.expect:
                self._publish_change(op.update)
            return True
        commit.clean()
        return False

    def _reschedule_ttl(self, ttl: float | None) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if ttl:
            self._arm_ttl(ttl)

    # -- snapshot hooks (crash-recovery plane, docs/DURABILITY.md) --------
    # The device register itself rides the engine's checkpoint blob; the
    # host bookkeeping here is one held-value record. States with armed
    # TTL timers or live change listeners opt OUT (NotImplemented) — they
    # hold commit references that cannot round-trip a snapshot, and the
    # manager then keeps the whole server on the replay-only recovery
    # path instead of persisting a lossy image.

    def snapshot_state(self) -> Any:
        if self._timer is not None or self._listeners:
            return NotImplemented
        held = None
        if self._held is not None:
            held = {"on_device": self._held.on_device,
                    "value": None if self._held.on_device
                    else self._held.value}
        return {"held": held}

    def restore_state(self, data: Any, sessions: dict) -> None:
        held = data["held"]
        if held is not None:
            # the creating commit is behind the snapshot boundary — its
            # log entry is already released, so a log-less stand-in
            # (clean() is a no-op) keeps the retained-commit discipline
            stand_in = Commit(0, None, 0.0, None, None)
            self._held = _Held(stand_in, value=held["value"],
                               on_device=held["on_device"])

    # -- edge read tier (docs/EDGE_READS.md) -------------------------------
    # The post-apply state row: device-resident values answer through
    # one device query (evaluated at delta-flush time, after the turn's
    # fused rows landed), host shadows answer from host state. An armed
    # TTL expires via a timer outside the apply path — invisible to the
    # delta plane's dirty marking — so TTL'd state opts out, retiring
    # its subscribers (the snapshot_state rule).

    def edge_state(self) -> Any:
        if self._timer is not None:
            return NotImplemented
        return ("val", self._value())

    # -- vector lane (batched server-side pump) ---------------------------
    # Eligible only in the steady device-resident state: value held ON
    # DEVICE, no TTL timer armed, no change listeners, devint payloads,
    # no TTL on the op. Under those gates each handler is exactly one
    # device op plus a held-commit swap, and within a vector run the
    # state stays in this regime (every eligible op leaves the value on
    # device), so stage-time classification remains valid at finalize.

    def vector_spec(self, operation: Any
                    ) -> tuple[int, int, int, int, int] | None:
        held = self._held
        if (held is None or not held.on_device or self._listeners
                or self._timer is not None):
            return None
        t = type(operation)
        if t is vc.CompareAndSet:
            if (operation.ttl or not _devint(operation.expect)
                    or not _devint(operation.update)):
                return None
            return (ops().OP_VALUE_CAS, operation.expect,
                    operation.update, 0, VK_CAS)
        if t is vc.GetAndSet:
            if operation.ttl or not _devint(operation.value):
                return None
            return (ops().OP_VALUE_GET_AND_SET, operation.value, 0, 0,
                    VK_GET_AND_SET)
        if t is vc.Set:
            if operation.ttl or not _devint(operation.value):
                return None
            return (ops().OP_VALUE_GET_AND_SET, operation.value, 0, 0,
                    VK_SET)
        return None

    def vector_finalize(self, kind: int, operation: Any, raw: int,
                        commit: Commit) -> Any:
        if kind == VK_CAS:
            # mirror of the generator's device-CAS arm (truthiness
            # included): success swaps the held commit, failure cleans
            if raw:
                self._held.discard()
                self._held = _Held(commit, on_device=True)
                return True
            commit.clean()
            return False
        # VK_GET_AND_SET / VK_SET: one GET_AND_SET, held commit swap
        # (the generator's _set_current with was_device=True, no TTL)
        self._held.discard()
        self._held = _Held(commit, on_device=True)
        return raw if kind == VK_GET_AND_SET else None

    # -- read pump (query vector lane) -------------------------------------
    # A get is one device query exactly when the value is held ON DEVICE
    # (host-shadowed and unset values answer from host state); listeners
    # and TTL timers don't gate reads — get never touches them.

    def query_spec(self, operation: Any
                   ) -> tuple[int, int, int, int, int] | None:
        if (type(operation) is vc.Get and self._held is not None
                and self._held.on_device):
            return (ops().OP_VALUE_GET, 0, 0, 0, QK_RAW)
        return None

    # -- change listeners (same protocol as the CPU machine) ---------------
    # listen/unlisten are host-state-only but still run as ordered jobs
    # (``yield from ()``): a later listen must not observe state ahead of
    # an earlier in-flight set/CAS chain's publish.

    def listen(self, commit: Commit[vc.Listen]) -> None:
        yield from ()
        previous = self._listeners.get(commit.session.id)
        if previous is not None:
            previous.clean()
        self._listeners[commit.session.id] = commit

    def unlisten(self, commit: Commit[vc.Unlisten]) -> None:
        yield from ()
        previous = self._listeners.pop(commit.session.id, None)
        if previous is not None:
            previous.clean()
        commit.clean()

    def _publish_change(self, value: Any) -> None:
        for listen_commit in list(self._listeners.values()):
            if listen_commit.session.is_open:
                listen_commit.session.publish("change", value)

    def close(self, session: Any) -> None:
        listen_commit = self._listeners.pop(session.id, None)
        if listen_commit is not None:
            listen_commit.clean()

    def delete(self) -> None:
        def chain():
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            if self._held is not None:
                if self._held.on_device:
                    # reset for group reuse
                    yield from self._cmd(ops().OP_VALUE_SET, 0)
                self._held.discard()
                self._held = None
            for listen_commit in self._listeners.values():
                listen_commit.clean()
            self._listeners.clear()

        self._run_excl(chain())
        super().delete()


# ---------------------------------------------------------------------------
# map
# ---------------------------------------------------------------------------

class DeviceMapState(DeviceBackedStateMachine):
    """Hashed map: int32 (key, value) entries live in the device probe
    table; overflow and non-int32 payloads take the host shadow — a put
    into a full device pool SUCCEEDS transparently (SURVEY.md §7.3 #1
    "eviction-to-host for overflow"; the reference ``MapState.java:32``
    has no capacity bound, so neither may we)."""

    def __init__(self, engine: DeviceEngine, group: int) -> None:
        super().__init__(engine, group)
        # key -> _Held; on_device=True ⇒ value lives in the device table
        self._held: dict[Any, _Held] = {}

    # -- internals ---------------------------------------------------------

    def _store(self, key: Any, value: Any, commit: Commit, ttl: float | None):
        """Insert/overwrite ``key``; returns the previous value."""
        previous_held = self._held.get(key)
        previous = self._read(key)
        if previous_held is not None:
            on_device = previous_held.on_device
            previous_held.discard()
        else:
            on_device = False
        if on_device:
            if _devint(value):
                yield from self._cmd(ops().OP_MAP_PUT, key, value)
                held = _Held(commit, on_device=True)
            else:
                yield from self._cmd(ops().OP_MAP_REMOVE, key)
                held = _Held(commit, value=value)
        else:
            if previous_held is None and _devint(key) and _devint(value):
                placed = yield from self._cmd(ops().OP_MAP_PUT, key, value)
            else:
                placed = FAIL()
            if placed != FAIL():
                held = _Held(commit, on_device=True)
            else:
                held = _Held(commit, value=value)
        self._held[key] = held
        if ttl:
            def expire() -> None:
                def chain():
                    if self._held.get(key) is held:
                        yield from self._evict(key, held)

                self._spawn(chain())

            held.timer = self.executor.schedule(ttl, expire)
        return previous

    def _read(self, key: Any) -> Any:
        held = self._held.get(key)
        if held is None:
            return None
        if held.on_device:
            return self._qry(ops().OP_MAP_GET, key)
        return held.value

    def _evict(self, key: Any, held: _Held):
        del self._held[key]
        if held.on_device:
            yield from self._cmd(ops().OP_MAP_REMOVE, key)
        held.discard()

    # -- queries -----------------------------------------------------------

    def contains_key(self, commit: Commit[cc.MapContainsKey]) -> bool:
        try:
            return commit.operation.key in self._held
        finally:
            commit.close()

    def contains_value(self, commit: Commit[cc.MapContainsValue]) -> bool:
        try:
            value = commit.operation.value
            if _devint(value) and any(
                    h.on_device for h in self._held.values()):
                if self._qry(ops().OP_MAP_CONTAINS_VALUE, value):
                    return True
            return any((not h.on_device) and h.value == value
                       for h in self._held.values())
        finally:
            commit.close()

    def get(self, commit: Commit[cc.MapGet]) -> Any:
        try:
            return self._read(commit.operation.key)
        finally:
            commit.close()

    def get_or_default(self, commit: Commit[cc.MapGetOrDefault]) -> Any:
        try:
            if commit.operation.key in self._held:
                return self._read(commit.operation.key)
            return commit.operation.default
        finally:
            commit.close()

    def is_empty(self, commit: Commit[cc.MapIsEmpty]) -> bool:
        try:
            return not self._held
        finally:
            commit.close()

    def size(self, commit: Commit[cc.MapSize]) -> int:
        try:
            return len(self._held)
        finally:
            commit.close()

    # -- read pump (query vector lane) -------------------------------------
    # A keyed read is one device query exactly when the key's value is
    # held ON DEVICE; absent keys and host-shadowed values answer from
    # host state and keep the handler path.

    def query_spec(self, operation: Any
                   ) -> tuple[int, int, int, int, int] | None:
        t = type(operation)
        if t is cc.MapGet or t is cc.MapGetOrDefault:
            held = self._held.get(operation.key)
            if held is not None and held.on_device:
                return (ops().OP_MAP_GET, operation.key, 0, 0, QK_RAW)
        return None

    # -- commands ----------------------------------------------------------

    def put(self, commit: Commit[cc.MapPut]) -> Any:
        op = commit.operation
        return (yield from self._store(op.key, op.value, commit, op.ttl))

    def put_if_absent(self, commit: Commit[cc.MapPutIfAbsent]) -> Any:
        op = commit.operation
        if op.key in self._held:
            value = self._read(op.key)
            commit.clean()
            return value
        yield from self._store(op.key, op.value, commit, op.ttl)
        return None

    def remove(self, commit: Commit[cc.MapRemove]) -> Any:
        key = commit.operation.key
        commit.clean()
        held = self._held.get(key)
        if held is None:
            return None
        value = self._read(key)
        yield from self._evict(key, held)
        return value

    def remove_if_present(self, commit: Commit[cc.MapRemoveIfPresent]) -> bool:
        op = commit.operation
        commit.clean()
        held = self._held.get(op.key)
        if held is None or self._read(op.key) != op.value:
            return False
        yield from self._evict(op.key, held)
        return True

    def replace(self, commit: Commit[cc.MapReplace]) -> Any:
        op = commit.operation
        if op.key not in self._held:
            commit.clean()
            return None
        return (yield from self._store(op.key, op.value, commit, op.ttl))

    def replace_if_present(self, commit: Commit[cc.MapReplaceIfPresent]) -> bool:
        op = commit.operation
        if op.key not in self._held or self._read(op.key) != op.expect:
            commit.clean()
            return False
        yield from self._store(op.key, op.value, commit, op.ttl)
        return True

    def clear(self, commit: Commit[cc.MapClear]) -> None:
        if any(h.on_device for h in self._held.values()):
            yield from self._cmd(ops().OP_MAP_CLEAR)
        for held in self._held.values():
            held.discard()
        self._held.clear()
        commit.clean()

    # -- snapshot hooks (crash-recovery plane, docs/DURABILITY.md) --------
    # The device probe table rides the engine's checkpoint blob; the
    # host bookkeeping is one record per key (device residency flag +
    # the host-shadow value). Armed per-key TTL timers hold commit
    # references that cannot round-trip — opt out (NotImplemented) and
    # keep the whole manager on replay-only recovery, like the value
    # machine.

    def snapshot_state(self) -> Any:
        if any(h.timer is not None for h in self._held.values()):
            return NotImplemented
        return {"held": [(k, h.on_device,
                          None if h.on_device else h.value)
                         for k, h in self._held.items()]}

    def restore_state(self, data: Any, sessions: dict) -> None:
        for key, on_device, value in data["held"]:
            # creating commits are behind the snapshot boundary: log-less
            # stand-ins (clean() is a no-op) keep the retained-commit
            # discipline
            self._held[key] = _Held(Commit(0, None, 0.0, None, None),
                                    value=value, on_device=on_device)

    # -- edge read tier (docs/EDGE_READS.md): full-state delta ------------
    # Armed per-key TTLs opt out (timers fire outside the apply path —
    # the value machine's rule); device-resident values gather through
    # ONE batched query_step round, not a blocking round per key (this
    # runs on the apply plane's event loop every delta flush).

    def edge_state(self) -> Any:
        if any(h.timer is not None for h in self._held.values()):
            return NotImplemented
        out = {k: h.value for k, h in self._held.items()
               if not h.on_device}
        dev_keys = [k for k, h in self._held.items() if h.on_device]
        if dev_keys:
            n = len(dev_keys)
            raws = self._eng.run_query_vector(
                [self._group] * n, [ops().OP_MAP_GET] * n, dev_keys,
                [0] * n, [0] * n)
            out.update(zip(dev_keys, raws))
        return ("map", out)

    def delete(self) -> None:
        def chain():
            if any(h.on_device for h in self._held.values()):
                # reset for group reuse
                yield from self._cmd(ops().OP_MAP_CLEAR)
            for held in self._held.values():
                held.discard()
            self._held.clear()

        self._run_excl(chain())
        super().delete()


# ---------------------------------------------------------------------------
# set
# ---------------------------------------------------------------------------

class DeviceSetState(DeviceBackedStateMachine):
    """Set: int32 members live in the device probe table, overflow/non-int32
    members shadow host-side (reference ``SetState.java:32``)."""

    def __init__(self, engine: DeviceEngine, group: int) -> None:
        super().__init__(engine, group)
        self._held: dict[Any, _Held] = {}

    def add(self, commit: Commit[cc.SetAdd]) -> bool:
        op = commit.operation
        if op.value in self._held:
            commit.clean()
            return False
        if _devint(op.value):
            added = yield from self._cmd(ops().OP_SET_ADD, op.value)
        else:
            added = FAIL()
        if added not in (FAIL(), 0):
            held = _Held(commit, on_device=True)
        else:
            held = _Held(commit, value=op.value)
        self._held[op.value] = held
        if op.ttl:
            def expire() -> None:
                def chain():
                    if self._held.get(op.value) is held:
                        yield from self._evict(op.value, held)

                self._spawn(chain())

            held.timer = self.executor.schedule(op.ttl, expire)
        return True

    def _evict(self, value: Any, held: _Held):
        del self._held[value]
        if held.on_device:
            yield from self._cmd(ops().OP_SET_REMOVE, value)
        held.discard()

    def remove(self, commit: Commit[cc.SetRemove]) -> bool:
        commit.clean()
        held = self._held.get(commit.operation.value)
        if held is None:
            return False
        yield from self._evict(commit.operation.value, held)
        return True

    def contains(self, commit: Commit[cc.SetContains]) -> bool:
        try:
            return commit.operation.value in self._held
        finally:
            commit.close()

    def is_empty(self, commit: Commit[cc.SetIsEmpty]) -> bool:
        try:
            return not self._held
        finally:
            commit.close()

    def size(self, commit: Commit[cc.SetSize]) -> int:
        try:
            return len(self._held)
        finally:
            commit.close()

    def clear(self, commit: Commit[cc.SetClear]) -> None:
        if any(h.on_device for h in self._held.values()):
            yield from self._cmd(ops().OP_SET_CLEAR)
        for held in self._held.values():
            held.discard()
        self._held.clear()
        commit.clean()

    # -- snapshot hooks (crash-recovery plane, docs/DURABILITY.md) --------
    # Same shape as the map machine: members on the device table ride
    # the engine blob, host shadows serialize here; armed TTL timers
    # opt the machine out.

    def snapshot_state(self) -> Any:
        if any(h.timer is not None for h in self._held.values()):
            return NotImplemented
        return {"held": [(v, h.on_device) for v, h in self._held.items()]}

    def restore_state(self, data: Any, sessions: dict) -> None:
        for value, on_device in data["held"]:
            self._held[value] = _Held(Commit(0, None, 0.0, None, None),
                                      value=None if on_device else value,
                                      on_device=on_device)

    # -- edge read tier (docs/EDGE_READS.md): full-state delta ------------
    # (membership is host-authoritative — `contains` never queries the
    # device — so no device round is needed; TTLs opt out as above)

    def edge_state(self) -> Any:
        if any(h.timer is not None for h in self._held.values()):
            return NotImplemented
        return ("set", list(self._held.keys()))

    def delete(self) -> None:
        def chain():
            if any(h.on_device for h in self._held.values()):
                # reset for group reuse
                yield from self._cmd(ops().OP_SET_CLEAR)
            for held in self._held.values():
                held.discard()
            self._held.clear()

        self._run_excl(chain())
        super().delete()


class DeviceMultiMapState(DeviceBackedStateMachine):
    """Multimap: int32 (key, value) pairs live in the device pair-probe
    table (``ops/apply.py`` OP_MM_*), overflow and non-int32 payloads
    shadow host-side; the host retains commits per pair (the reference's
    nested ``Map<Object, Map<Object, Commit>>`` discipline,
    ``MultiMapState.java:30``)."""

    def __init__(self, engine: DeviceEngine, group: int) -> None:
        super().__init__(engine, group)
        # (key, value) -> _Held; on_device=True ⇒ pair lives on device
        self._held: dict[tuple, _Held] = {}

    def _evict(self, pair: tuple, held: _Held):
        del self._held[pair]
        if held.on_device:
            yield from self._cmd(ops().OP_MM_REMOVE_ENTRY, pair[0], pair[1])
        held.discard()

    def put(self, commit: Commit[cc.MultiMapPut]) -> bool:
        op = commit.operation
        pair = (op.key, op.value)
        if pair in self._held:
            commit.clean()
            return False
        if _devint(op.key) and _devint(op.value):
            placed = yield from self._cmd(ops().OP_MM_PUT, op.key, op.value)
        else:
            placed = FAIL()
        if placed not in (FAIL(), 0):
            held = _Held(commit, on_device=True)
        else:
            held = _Held(commit)
        self._held[pair] = held
        if op.ttl:
            def expire() -> None:
                def chain():
                    if self._held.get(pair) is held:
                        yield from self._evict(pair, held)

                self._spawn(chain())

            held.timer = self.executor.schedule(op.ttl, expire)
        return True

    def get(self, commit: Commit[cc.MultiMapGet]) -> list:
        try:
            key = commit.operation.key
            return [v for (k, v) in self._held if k == key]
        finally:
            commit.close()

    def remove(self, commit: Commit[cc.MultiMapRemove]) -> list:
        key = commit.operation.key
        commit.clean()
        pairs = [p for p in self._held if p[0] == key]
        if any(self._held[p].on_device for p in pairs):
            # drops every device pair
            yield from self._cmd(ops().OP_MM_REMOVE, key)
        out = []
        for pair in pairs:
            held = self._held.pop(pair)
            out.append(pair[1])
            held.discard()
        return out

    def remove_entry(self, commit: Commit[cc.MultiMapRemoveEntry]) -> bool:
        op = commit.operation
        commit.clean()
        held = self._held.get((op.key, op.value))
        if held is None:
            return False
        yield from self._evict((op.key, op.value), held)
        return True

    def contains_key(self, commit: Commit[cc.MultiMapContainsKey]) -> bool:
        try:
            key = commit.operation.key
            return any(k == key for (k, _v) in self._held)
        finally:
            commit.close()

    def contains_entry(self, commit: Commit[cc.MultiMapContainsEntry]) -> bool:
        # The host dict key IS the (key, value) pair, kept in lockstep
        # with the device table (TTLs run host-side), so it is
        # authoritative — no device round-trip needed.
        try:
            return (commit.operation.key,
                    commit.operation.value) in self._held
        finally:
            commit.close()

    def contains_value(self, commit: Commit[cc.MultiMapContainsValue]) -> bool:
        try:
            value = commit.operation.value
            return any(v == value for (_k, v) in self._held)
        finally:
            commit.close()

    def is_empty(self, commit: Commit[cc.MultiMapIsEmpty]) -> bool:
        try:
            return not self._held
        finally:
            commit.close()

    def size(self, commit: Commit[cc.MultiMapSize]) -> int:
        try:
            key = commit.operation.key
            if key is not None:
                return sum(1 for (k, _v) in self._held if k == key)
            return len(self._held)
        finally:
            commit.close()

    def clear(self, commit: Commit[cc.MultiMapClear]) -> None:
        if any(h.on_device for h in self._held.values()):
            yield from self._cmd(ops().OP_MM_CLEAR)
        for held in self._held.values():
            held.discard()
        self._held.clear()
        commit.clean()

    def delete(self) -> None:
        def chain():
            if any(h.on_device for h in self._held.values()):
                # reset for group reuse
                yield from self._cmd(ops().OP_MM_CLEAR)
            for held in self._held.values():
                held.discard()
            self._held.clear()

        self._run_excl(chain())
        super().delete()


# ---------------------------------------------------------------------------
# queue
# ---------------------------------------------------------------------------

class DeviceQueueState(DeviceBackedStateMachine):
    """FIFO queue: device ring holds int32 payloads, a host marker deque
    defines global order across device/host entries so interleaved
    overflow keeps exact FIFO semantics (reference ``QueueState.java:30``).

    Values are mirrored host-side so ``contains``/``remove(v)`` (which the
    device ring cannot serve from the middle) stay supported: a mid-ring
    removal drains and re-offers the ring minus the removed payload
    (``_tombstone_device``) — queue remove-by-value is rare, ring
    capacity small.
    """

    def __init__(self, engine: DeviceEngine, group: int) -> None:
        super().__init__(engine, group)
        self._queue: deque[_Held] = deque()  # live entries, global FIFO

    def _enqueue(self, commit: Commit, value: Any):
        if _devint(value):
            offered = yield from self._cmd(ops().OP_Q_OFFER, value)
        else:
            offered = 0
        if offered == 1:
            held = _Held(commit, value=value, on_device=True)
        else:
            held = _Held(commit, value=value)
        self._queue.append(held)
        return True

    def _device_poll(self):
        return (yield from self._cmd(ops().OP_Q_POLL))

    def _pop_head(self):
        held = self._queue.popleft()
        if held.on_device:
            yield from self._device_poll()
        held.discard()
        return held

    def add(self, commit: Commit[cc.QueueAdd]) -> bool:
        return (yield from self._enqueue(commit, commit.operation.value))

    def offer(self, commit: Commit[cc.QueueOffer]) -> bool:
        return (yield from self._enqueue(commit, commit.operation.value))

    def peek(self, commit: Commit[cc.QueuePeek]) -> Any:
        try:
            return self._queue[0].value if self._queue else None
        finally:
            commit.close()

    def poll(self, commit: Commit[cc.QueuePoll]) -> Any:
        commit.clean()
        if not self._queue:
            return None
        held = yield from self._pop_head()
        return held.value

    def element(self, commit: Commit[cc.QueueElement]) -> Any:
        yield from ()
        commit.clean()
        if not self._queue:
            raise ValueError("queue is empty")
        return self._queue[0].value

    def remove(self, commit: Commit[cc.QueueRemove]) -> Any:
        op = commit.operation
        commit.clean()
        if op.value is None:
            if not self._queue:
                raise ValueError("queue is empty")
            held = yield from self._pop_head()
            return held.value
        for held in self._queue:
            if held.value == op.value:
                if held is self._queue[0]:
                    yield from self._pop_head()
                else:
                    # mid-queue: tombstone; the device copy (if any) is
                    # drained when it reaches the ring head
                    self._queue.remove(held)
                    if held.on_device:
                        yield from self._tombstone_device(held)
                    held.discard()
                return True
        return False

    def _tombstone_device(self, held: _Held):
        # Re-synchronize the ring with the live deque: device entries
        # before this one are still live; we pop-and-reoffer the ring so
        # the removed payload is dropped. Device ring order == order of
        # on_device entries in self._queue, so draining/refilling keeps it.
        live_device = [h.value for h in self._queue if h.on_device]
        while (yield from self._device_poll()) != FAIL():
            pass
        for v in live_device:
            yield from self._cmd(ops().OP_Q_OFFER, v)

    def contains(self, commit: Commit[cc.QueueContains]) -> bool:
        try:
            return any(h.value == commit.operation.value
                       for h in self._queue)
        finally:
            commit.close()

    def is_empty(self, commit: Commit[cc.QueueIsEmpty]) -> bool:
        try:
            return not self._queue
        finally:
            commit.close()

    def size(self, commit: Commit[cc.QueueSize]) -> int:
        try:
            return len(self._queue)
        finally:
            commit.close()

    def clear(self, commit: Commit[cc.QueueClear]) -> None:
        if any(h.on_device for h in self._queue):
            yield from self._cmd(ops().OP_Q_CLEAR)
        for held in self._queue:
            held.discard()
        self._queue.clear()
        commit.clean()

    def delete(self) -> None:
        def chain():
            if any(h.on_device for h in self._queue):
                # reset for group reuse
                yield from self._cmd(ops().OP_Q_CLEAR)
            for held in self._queue:
                held.discard()
            self._queue.clear()

        self._run_excl(chain())
        super().delete()


# ---------------------------------------------------------------------------
# lock
# ---------------------------------------------------------------------------

class DeviceLockState(DeviceBackedStateMachine):
    """Mutex on the device lock kernel: waiter id = the Lock commit index
    (unique per acquire, same as the CPU machine), grants delivered as
    "lock" session events when the device emits EV_LOCK_GRANT.

    Timeouts run host-side through the replicated log-time timers and
    resolve the grant-vs-timeout race via OP_LOCK_CANCEL (totally ordered
    in the device log). Session death releases held locks and dequeues
    waiters — the capability fix over the reference, preserved from the
    CPU machine (``coordination/state.py:21-23``).
    """

    SETTLES = True  # grants arrive as device events; chains resume settled

    def __init__(self, engine: DeviceEngine, group: int) -> None:
        super().__init__(engine, group)
        self._waiters: dict[int, Commit] = {}   # waiter id -> Lock commit
        self._holder_id: int | None = None
        self._timers: dict[int, Any] = {}
        self._overflow: deque[int] = deque()    # ids the device ring rejected

    # -- event pump --------------------------------------------------------

    def _pump(self):
        for _seq, code, target, _arg in self._events():
            if code != ops().EV_LOCK_GRANT:
                continue
            waiter = self._waiters.get(target)
            if waiter is None:
                # grant to a dead waiter (cancelled/closed): release it so
                # the queue keeps moving
                yield from self._cmd(ops().OP_LOCK_RELEASE, target)
                continue
            self._holder_id = target
            timer = self._timers.pop(target, None)
            if timer is not None:
                timer.cancel()
            if waiter.session.is_open:
                waiter.session.publish(
                    "lock", {"id": target, "acquired": True})
        yield from self._flush_overflow()

    def _flush_overflow(self):
        while self._overflow:
            wid = self._overflow[0]
            if wid not in self._waiters:
                self._overflow.popleft()
                continue
            result = yield from self._cmd(ops().OP_LOCK_ACQUIRE, wid, -1)
            if result == 1:  # granted immediately
                self._overflow.popleft()
                self._on_grant(wid)
            elif result == 2:  # queued on device
                self._overflow.popleft()
            else:  # ring still full
                break

    def _on_grant(self, wid: int) -> None:
        waiter = self._waiters.get(wid)
        self._holder_id = wid
        timer = self._timers.pop(wid, None)
        if timer is not None:
            timer.cancel()
        if waiter is not None and waiter.session.is_open:
            waiter.session.publish("lock", {"id": wid, "acquired": True})

    # -- handlers ----------------------------------------------------------

    def lock(self, commit: Commit[oc.Lock]) -> int:
        wid = commit.index
        timeout = commit.operation.timeout
        yield from self._pump()
        if timeout == 0:
            result = yield from self._cmd(ops().OP_LOCK_ACQUIRE, wid, 0)
            if result == 1:
                self._waiters[wid] = commit
                self._on_grant(wid)
            else:
                commit.session.publish(
                    "lock", {"id": wid, "acquired": False})
                commit.clean()
            yield from self._pump()
            return wid
        self._waiters[wid] = commit
        if self._overflow:
            self._overflow.append(wid)  # preserve FIFO behind overflow
        else:
            result = yield from self._cmd(ops().OP_LOCK_ACQUIRE, wid, -1)
            if result == 1:
                self._on_grant(wid)
            elif result == 0:  # device wait ring full — host absorbs
                self._overflow.append(wid)
        if timeout and timeout > 0 and self._holder_id != wid:
            def expire() -> None:
                def chain():
                    self._timers.pop(wid, None)
                    yield from self._cancel_waiter(wid, publish=True)

                self._spawn(chain())

            self._timers[wid] = self.executor.schedule(timeout, expire)
        yield from self._pump()
        return wid

    def _cancel_waiter(self, wid: int, publish: bool):
        waiter = self._waiters.get(wid)
        if waiter is None or self._holder_id == wid:
            return
        if wid in self._overflow:
            self._overflow.remove(wid)
            outcome = 1
        else:
            outcome = yield from self._cmd(ops().OP_LOCK_CANCEL, wid)
        if outcome == 2:
            # race resolved in our favor: already granted — the grant
            # event is (or will be) in the pump
            yield from self._pump()
            return
        del self._waiters[wid]
        if publish and waiter.session.is_open:
            waiter.session.publish("lock", {"id": wid, "acquired": False})
        waiter.clean()
        yield from self._pump()

    def unlock(self, commit: Commit[oc.Unlock]) -> None:
        try:
            yield from self._pump()
            if self._holder_id is None:
                return
            holder = self._waiters.get(self._holder_id)
            if holder is None or holder.session.id != commit.session.id:
                raise ValueError("not the lock holder")
            yield from self._release_holder()
        finally:
            commit.clean()

    def _release_holder(self):
        wid = self._holder_id
        holder = self._waiters.pop(wid, None)
        self._holder_id = None
        if holder is not None:
            holder.clean()
        yield from self._cmd(ops().OP_LOCK_RELEASE, wid)
        yield from self._pump()

    # -- session lifecycle -------------------------------------------------

    def close(self, session: Any) -> None:
        def chain():
            yield from self._pump()
            for wid in [w for w, c in self._waiters.items()
                        if c.session.id == session.id
                        and w != self._holder_id]:
                yield from self._cancel_waiter(wid, publish=False)
            if self._holder_id is not None:
                holder = self._waiters.get(self._holder_id)
                if holder is not None and holder.session.id == session.id:
                    yield from self._release_holder()

        self._run_excl(chain())

    def delete(self) -> None:
        def chain():
            for timer in self._timers.values():
                timer.cancel()
            self._timers.clear()
            # Reset the device lock for group reuse: dequeue every waiter
            # FIRST so releasing the holder cannot grant one of them.
            for wid in list(self._waiters):
                if wid != self._holder_id and wid not in self._overflow:
                    yield from self._cmd(ops().OP_LOCK_CANCEL, wid)
            if self._holder_id is not None:
                yield from self._cmd(ops().OP_LOCK_RELEASE, self._holder_id)
                self._holder_id = None
            for waiter in self._waiters.values():
                waiter.clean()
            self._waiters.clear()
            self._overflow.clear()

        self._run_excl(chain())
        super().delete()


# ---------------------------------------------------------------------------
# leader election
# ---------------------------------------------------------------------------

class DeviceLeaderElectionState(DeviceBackedStateMachine):
    """Leader election on the device election kernel: candidate id = the
    client session id (CPU machine keys listeners by session), epoch =
    device log index of the winning listen (an opaque fencing token to the
    client, exactly as the reference's commit-index epoch,
    ``LeaderElectionState.java:31``)."""

    SETTLES = True  # promotions arrive as device events

    def __init__(self, engine: DeviceEngine, group: int) -> None:
        super().__init__(engine, group)
        self._listens: dict[int, Commit] = {}   # session id -> Listen commit
        self._leader: int | None = None         # session id
        self._epoch: int | None = None
        self._overflow: deque[int] = deque()

    def _pump(self):
        for _seq, code, target, arg in self._events():
            if code != ops().EV_ELECT:
                continue
            listen = self._listens.get(target)
            if listen is None:
                # promoted a dead candidate: resign it to move succession
                yield from self._cmd(ops().OP_ELECT_RESIGN, target)
                continue
            self._leader, self._epoch = target, arg
            if listen.session.is_open:
                listen.session.publish("elect", arg)
        yield from self._flush_overflow()

    def _flush_overflow(self):
        while self._overflow:
            sid = self._overflow[0]
            if sid not in self._listens:
                self._overflow.popleft()
                continue
            result = yield from self._cmd(ops().OP_ELECT_LISTEN, sid)
            if result == FAIL():
                break  # listener ring still full
            self._overflow.popleft()
            if result > 0:
                self._on_elected(sid, result)

    def _on_elected(self, sid: int, epoch: int) -> None:
        self._leader, self._epoch = sid, epoch
        listen = self._listens.get(sid)
        if listen is not None and listen.session.is_open:
            listen.session.publish("elect", epoch)

    def listen(self, commit: Commit[oc.ElectionListen]) -> None:
        sid = commit.session.id
        yield from self._pump()
        previous = self._listens.get(sid)
        if previous is not None:
            previous.clean()
            self._listens[sid] = commit
            yield from self._pump()
            return
        self._listens[sid] = commit
        if self._overflow:
            self._overflow.append(sid)
        else:
            result = yield from self._cmd(ops().OP_ELECT_LISTEN, sid)
            if result == FAIL():
                self._overflow.append(sid)  # host absorbs ring overflow
            elif result > 0:
                self._on_elected(sid, result)
        yield from self._pump()

    def unlisten(self, commit: Commit[oc.ElectionUnlisten]) -> None:
        try:
            yield from self._resign(commit.session.id)
        finally:
            commit.clean()

    def is_leader(self, commit: Commit[oc.ElectionIsLeader]) -> bool:
        # NO pump here: queries execute on a single server, and _pump can
        # issue device commands (overflow flush / dead-candidate resign)
        # that would fork that server's device log from its peers. The
        # mirror is always current as of the last command (every command
        # settles its events before returning), which is exactly the
        # linearization point a query may observe.
        try:
            return self._epoch is not None \
                and self._epoch == commit.operation.epoch
        finally:
            commit.close()

    def _resign(self, sid: int):
        yield from self._pump()
        listen = self._listens.pop(sid, None)
        if listen is None:
            return
        listen.clean()
        if sid in self._overflow:
            self._overflow.remove(sid)
        else:
            yield from self._cmd(ops().OP_ELECT_RESIGN, sid)
        if self._leader == sid:
            self._leader = self._epoch = None
        yield from self._pump()

    def close(self, session: Any) -> None:
        self._run_excl(self._resign(session.id))

    def delete(self) -> None:
        def chain():
            # Reset the device election for group reuse: unlist waiters
            # first, resign the leader last (empty ring → no succession
            # event).
            for sid in list(self._listens):
                if sid != self._leader and sid not in self._overflow:
                    yield from self._cmd(ops().OP_ELECT_RESIGN, sid)
            if self._leader is not None:
                yield from self._cmd(ops().OP_ELECT_RESIGN, self._leader)
                self._leader = self._epoch = None
            for listen in self._listens.values():
                listen.clean()
            self._listens.clear()
            self._overflow.clear()

        self._run_excl(chain())
        super().delete()


# ---------------------------------------------------------------------------
# registry + lazy opcode access
# ---------------------------------------------------------------------------

_ops_mod = None


def ops():
    """The device opcode/event-code module, imported lazily so constructing
    a pure-CPU cluster never imports JAX. Memoized: the import-machinery
    lookup (sys.modules + parent resolution) was the single hottest line
    of the SPI burst profile when paid per op."""
    global _ops_mod
    if _ops_mod is None:
        from ..ops import apply as _ops_mod_local
        _ops_mod = _ops_mod_local
    return _ops_mod


def FAIL() -> int:
    return INT32_MIN


def device_machine_for(machine_cls: type,
                       resource_config: Any = None) -> type | None:
    """Device-backed equivalent for a CPU state machine class, or ``None``
    when the type must stay on the CPU path: topic/group/bus are
    host-push-bound (their work is session event fan-out and out-of-band
    transport, not state-machine compute — the device topic kernel serves
    the raw batch path instead), and any user-defined machine has
    arbitrary Python state.

    ``resource_config`` (the engine's provisioned pools,
    ``DeviceEngineConfig.resource``) gates placement further: a type
    whose pool is compiled out of this engine (size 0) falls back to the
    CPU machine — the pool-provisioning deployment knob must degrade to
    the slower path, never to FAIL-sentinel device ops."""
    from ..atomic.state import AtomicValueState
    from ..collections.state import (
        MapState, MultiMapState, QueueState, SetState)
    from ..coordination.state import LeaderElectionState, LockState
    cls = {
        AtomicValueState: DeviceAtomicValueState,
        MapState: DeviceMapState,
        MultiMapState: DeviceMultiMapState,
        SetState: DeviceSetState,
        QueueState: DeviceQueueState,
        LockState: DeviceLockState,
        LeaderElectionState: DeviceLeaderElectionState,
    }.get(machine_cls)
    if cls is None or resource_config is None:
        return cls
    rc = resource_config
    required = {
        DeviceMapState: rc.map_slots,
        DeviceSetState: rc.set_slots,
        DeviceQueueState: rc.queue_slots,
        DeviceMultiMapState: rc.multimap_slots,
        # lock grants and election promotions ride the event outbox
        DeviceLockState: min(rc.wait_slots, rc.event_slots),
        DeviceLeaderElectionState: min(rc.listener_slots, rc.event_slots),
    }.get(cls, 1)  # value/long registers always exist
    return cls if required > 0 else None
