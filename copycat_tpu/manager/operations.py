"""Catalog + envelope operations (reference serializer ids 30-38).

- Catalog ops manage the name->resource registry: ``GetResource`` (id 35,
  LINEARIZABLE), ``CreateResource`` (36, carries the state-machine class as a
  registered class reference, cf. ``CreateResource.java:55-66``),
  ``DeleteResource`` (37), ``ResourceExists`` (38, LINEARIZABLE query).
- Envelope ops route an operation to a resource instance: ``InstanceCommand``
  (30) / ``InstanceQuery`` (31); ``InstanceEvent`` (32) routes session events
  back, filtered client-side by instance id.

All are generic field-list serializable (``Message``), so the native
codec walks instance envelopes — the wrapper around every routed op —
entirely in C.
"""

from __future__ import annotations

from typing import Any

from ..io.serializer import serialize_with
from ..protocol.messages import Message
from ..protocol.operations import Command, CommandConsistency, Persistence, Query, QueryConsistency


class KeyOperation(Message):
    """Base for catalog ops addressing a resource by name (``KeyOperation.java``)."""

    _fields = ("key",)

    def __init__(self, key: str = "") -> None:
        self.key = key


@serialize_with(35)
class GetResource(KeyOperation, Command):
    """Get-or-create the resource and attach (at most) one instance per client
    session; returns the instance id."""

    _fields = ("key", "state_machine")

    def __init__(self, key: str = "", state_machine: type | None = None) -> None:
        super().__init__(key)
        self.state_machine = state_machine

    def consistency(self) -> CommandConsistency:
        return CommandConsistency.LINEARIZABLE


@serialize_with(36)
class CreateResource(GetResource):
    """Like GetResource but always creates a fresh instance (unique session)."""


@serialize_with(37)
class DeleteResource(Message, Command):
    """Deletes a resource's replicated state entirely (by instance id)."""

    _fields = ("instance_id",)

    def __init__(self, instance_id: int = 0) -> None:
        self.instance_id = instance_id

    def persistence(self) -> Persistence:
        return Persistence.PERSISTENT


@serialize_with(38)
class ResourceExists(KeyOperation, Query):
    def consistency(self) -> QueryConsistency:
        return QueryConsistency.LINEARIZABLE


class InstanceOperation(Message):
    """Envelope (instance id, inner operation)."""

    _fields = ("resource", "operation")

    def __init__(self, resource: int = 0, operation: Any = None) -> None:
        self.resource = resource
        self.operation = operation


@serialize_with(30)
class InstanceCommand(InstanceOperation, Command):
    def consistency(self) -> CommandConsistency | None:
        if isinstance(self.operation, Command):
            return self.operation.consistency()
        return CommandConsistency.LINEARIZABLE

    def persistence(self) -> Persistence:
        if isinstance(self.operation, Command):
            return self.operation.persistence()
        return Persistence.PERSISTENT


@serialize_with(31)
class InstanceQuery(InstanceOperation, Query):
    def consistency(self) -> QueryConsistency | None:
        if isinstance(self.operation, Query):
            return self.operation.consistency()
        return QueryConsistency.LINEARIZABLE


@serialize_with(32)
class InstanceEvent(Message):
    """Event payload envelope: (instance id, message) (``InstanceEvent.java``)."""

    _fields = ("resource", "message")

    def __init__(self, resource: int = 0, message: Any = None) -> None:
        self.resource = resource
        self.message = message
