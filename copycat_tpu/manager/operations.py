"""Catalog + envelope operations (reference serializer ids 30-38).

- Catalog ops manage the name->resource registry: ``GetResource`` (id 35,
  LINEARIZABLE), ``CreateResource`` (36, carries the state-machine class as a
  registered class reference, cf. ``CreateResource.java:55-66``),
  ``DeleteResource`` (37), ``ResourceExists`` (38, LINEARIZABLE query).
- Envelope ops route an operation to a resource instance: ``InstanceCommand``
  (30) / ``InstanceQuery`` (31); ``InstanceEvent`` (32) routes session events
  back, filtered client-side by instance id.
"""

from __future__ import annotations

from typing import Any

from ..io.buffer import BufferInput, BufferOutput
from ..io.serializer import Serializer, serialize_with
from ..protocol.operations import Command, CommandConsistency, Persistence, Query, QueryConsistency


class KeyOperation:
    """Base for catalog ops addressing a resource by name (``KeyOperation.java``)."""

    def __init__(self, key: str = "") -> None:
        self.key = key

    def write_object(self, buf: BufferOutput, serializer: Serializer) -> None:
        buf.write_utf8(self.key)

    def read_object(self, buf: BufferInput, serializer: Serializer) -> None:
        self.key = buf.read_utf8()


@serialize_with(35)
class GetResource(KeyOperation, Command):
    """Get-or-create the resource and attach (at most) one instance per client
    session; returns the instance id."""

    def __init__(self, key: str = "", state_machine: type | None = None) -> None:
        super().__init__(key)
        self.state_machine = state_machine

    def consistency(self) -> CommandConsistency:
        return CommandConsistency.LINEARIZABLE

    def write_object(self, buf: BufferOutput, serializer: Serializer) -> None:
        super().write_object(buf, serializer)
        serializer.write_class(self.state_machine, buf)

    def read_object(self, buf: BufferInput, serializer: Serializer) -> None:
        super().read_object(buf, serializer)
        self.state_machine = serializer.read_object(buf)


@serialize_with(36)
class CreateResource(GetResource):
    """Like GetResource but always creates a fresh instance (unique session)."""


@serialize_with(37)
class DeleteResource(Command):
    """Deletes a resource's replicated state entirely (by instance id)."""

    def __init__(self, instance_id: int = 0) -> None:
        self.instance_id = instance_id

    def persistence(self) -> Persistence:
        return Persistence.PERSISTENT

    def write_object(self, buf: BufferOutput, serializer: Serializer) -> None:
        buf.write_i64(self.instance_id)

    def read_object(self, buf: BufferInput, serializer: Serializer) -> None:
        self.instance_id = buf.read_i64()


@serialize_with(38)
class ResourceExists(KeyOperation, Query):
    def consistency(self) -> QueryConsistency:
        return QueryConsistency.LINEARIZABLE


class InstanceOperation:
    """Envelope (instance id, inner operation)."""

    def __init__(self, resource: int = 0, operation: Any = None) -> None:
        self.resource = resource
        self.operation = operation

    def write_object(self, buf: BufferOutput, serializer: Serializer) -> None:
        buf.write_i64(self.resource)
        serializer.write_object(self.operation, buf)

    def read_object(self, buf: BufferInput, serializer: Serializer) -> None:
        self.resource = buf.read_i64()
        self.operation = serializer.read_object(buf)


@serialize_with(30)
class InstanceCommand(InstanceOperation, Command):
    def consistency(self) -> CommandConsistency | None:
        if isinstance(self.operation, Command):
            return self.operation.consistency()
        return CommandConsistency.LINEARIZABLE

    def persistence(self) -> Persistence:
        if isinstance(self.operation, Command):
            return self.operation.persistence()
        return Persistence.PERSISTENT


@serialize_with(31)
class InstanceQuery(InstanceOperation, Query):
    def consistency(self) -> QueryConsistency | None:
        if isinstance(self.operation, Query):
            return self.operation.consistency()
        return QueryConsistency.LINEARIZABLE


@serialize_with(32)
class InstanceEvent:
    """Event payload envelope: (instance id, message) (``InstanceEvent.java``)."""

    def __init__(self, resource: int = 0, message: Any = None) -> None:
        self.resource = resource
        self.message = message

    def write_object(self, buf: BufferOutput, serializer: Serializer) -> None:
        buf.write_i64(self.resource)
        serializer.write_object(self.message, buf)

    def read_object(self, buf: BufferInput, serializer: Serializer) -> None:
        self.resource = buf.read_i64()
        self.message = serializer.read_object(buf)
