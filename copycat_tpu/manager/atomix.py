"""Cluster facade (reference ``Atomix.java:58``, ``AtomixClient.java:35``,
``AtomixReplica.java:45``, ``AtomixServer.java:40``).

- :class:`Atomix` — ``exists/get/create/close`` over a RaftClient
- :class:`AtomixClient` — stateless node (client only)
- :class:`AtomixReplica` — client + server in one process, client pinned to the
  colocated server (the reference's CombinedTransport/ConnectionStrategy)
- :class:`AtomixServer` — standalone server (no client facade)

Configuration is via typed keyword arguments plus a chained ``Builder`` for
API parity with the reference's ``builder()`` surface (SURVEY.md §5.6).
"""

from __future__ import annotations

from typing import Any, Type, TypeVar

from ..utils import knobs

from ..client.client import PinnedConnectionStrategy, RaftClient
from ..io.transport import Address, Transport
from ..resource.resource import Resource, resource_state_machine_of
from ..server.log import Storage
from ..server.raft import RaftServer
from ..utils.managed import Managed
from .instance import InstanceClient
from .operations import CreateResource, GetResource, ResourceExists
from .state import ResourceManager

# Register the built-in resource library with the serializer. The wire
# protocol carries class REFERENCES by registry id (the documented
# deviation from the reference's Class.forName — serializer.py), so a
# server must know the whole catalog before the first client names a
# resource class it never imported itself. Single-process tests import
# everything anyway; a standalone `copycat-server` would otherwise fail
# to decode GetResource("x", DistributedAtomicValue) from a remote
# client ("unknown class id" — found driving the packaged server +
# client examples cross-process).
from .. import atomic as _atomic  # noqa: F401,E402
from .. import collections as _collections  # noqa: F401,E402
from .. import coordination as _coordination  # noqa: F401,E402

R = TypeVar("R", bound=Resource)


def _manager_factory(executor: str, engine_config: Any,
                     groups: int | None) -> tuple[Any, int]:
    """Resolve the group count (constructor arg > COPYCAT_GROUPS, gated
    by COPYCAT_MULTI_GROUP) and build the per-group ResourceManager
    factory — one manager per Raft group, sharing ONE device engine so
    every group's device-backed resources ride the same [G×P] tensor
    plane (docs/SHARDING.md)."""
    if groups is None:
        groups = max(1, knobs.get_int("COPYCAT_GROUPS"))
    if not knobs.get_bool("COPYCAT_MULTI_GROUP"):
        groups = 1
    if groups == 1:
        return ResourceManager(executor=executor,
                               engine_config=engine_config), 1
    shared_engine = None
    if executor == "tpu":
        from .device_executor import DeviceEngine
        shared_engine = DeviceEngine(engine_config)

    def factory(g: int) -> ResourceManager:
        return ResourceManager(executor=executor,
                               engine_config=engine_config,
                               group_id=g, num_groups=groups,
                               engine=shared_engine)

    return factory, groups


class Atomix(Managed):
    """Async facade over the resource catalog."""

    def __init__(self, client: RaftClient) -> None:
        super().__init__()
        self.client = client
        self._resources: dict[str, Resource] = {}  # get() singleton cache per node

    async def exists(self, key: str) -> bool:
        return bool(await self.client.submit(ResourceExists(key)))

    @staticmethod
    async def _build_facade(instance: InstanceClient, resource_type: type,
                            factory: Any):
        """Build (factory or reflective constructor) + validate a facade.

        On a bad factory the LOCAL instance state is closed (listener
        wrappers); the server-side virtual session is reclaimed when the
        parent client session closes or times out — the same fate as any
        abandoned instance in the reference (there is deliberately no
        instance-close catalog op; see manager/operations.py)."""
        build = factory if factory is not None else resource_type
        try:
            resource = build(instance)
            if not isinstance(resource, resource_type):
                raise TypeError(
                    f"factory built {type(resource).__name__}, not a "
                    f"{resource_type.__name__}")
        except BaseException:
            try:
                await instance.close()
            except Exception:  # noqa: BLE001 — best-effort cleanup
                pass
            raise
        return resource

    async def get(self, key: str, resource_type: Type[R],
                  factory: Any = None) -> R:
        """Singleton-per-node resource handle (reference ``Atomix.get:205-208``).

        ``factory`` (reference's ``Atomix.get(key, type, factory)``
        overload) builds the client-side facade from its
        ``InstanceClient`` instead of the reflective one-arg constructor
        — for subclassed/wrapped resources; the replicated state machine
        still resolves from ``resource_type``. The built object must be
        a ``resource_type`` instance (the singleton cache's type check
        stays meaningful). The node-local singleton wins, as in the
        reference: on a cache hit the EXISTING facade is returned and
        ``factory`` is not invoked — pass the factory at first get (or
        use :meth:`create`) when a custom facade matters."""
        cached = self._resources.get(key)
        if cached is not None:
            if not isinstance(cached, resource_type):
                raise ValueError(
                    f"resource '{key}' already open as {type(cached).__name__}")
            return cached
        machine = resource_state_machine_of(resource_type)
        instance_id = await self.client.submit(GetResource(key, machine))
        resource = await self._build_facade(
            InstanceClient(instance_id, self.client,
                           on_delete=lambda: self._evict(key, instance_id)),
            resource_type, factory)
        self._resources[key] = resource
        return resource

    def _evict(self, key: str, instance_id: int) -> None:
        """Drop the get() singleton for a deleted resource (only if the
        cache still holds THAT instance — a re-created resource under the
        same key must not be evicted by a stale facade's delete)."""
        cached = self._resources.get(key)
        if cached is not None and getattr(cached.client, "instance_id",
                                          None) == instance_id:
            del self._resources[key]

    async def create(self, key: str, resource_type: Type[R],
                     factory: Any = None) -> R:
        """Fresh instance with its own virtual session per call
        (reference ``Atomix.create:303-306``; ``factory`` per the
        ``create(key, type, factory)`` overload — see :meth:`get`)."""
        machine = resource_state_machine_of(resource_type)
        instance_id = await self.client.submit(CreateResource(key, machine))
        return await self._build_facade(
            InstanceClient(instance_id, self.client), resource_type, factory)

    async def _do_open(self) -> None:
        await self.client.open()

    async def _do_close(self) -> None:
        self._resources.clear()
        await self.client.close()


class _Builder:
    """Chained builder for API parity with the reference."""

    def __init__(self, cls: type, address: Address | None, members: list[Address]) -> None:
        self._cls = cls
        self._kwargs: dict[str, Any] = {"address": address, "members": members}

    def with_transport(self, transport: Transport) -> "_Builder":
        self._kwargs["transport"] = transport
        return self

    def with_storage(self, storage: Storage) -> "_Builder":
        self._kwargs["storage"] = storage
        return self

    def with_election_timeout(self, timeout: float) -> "_Builder":
        self._kwargs["election_timeout"] = timeout
        return self

    def with_heartbeat_interval(self, interval: float) -> "_Builder":
        self._kwargs["heartbeat_interval"] = interval
        return self

    def with_session_timeout(self, timeout: float) -> "_Builder":
        self._kwargs["session_timeout"] = timeout
        return self

    def with_stats_port(self, port: int,
                        host: str = "127.0.0.1") -> "_Builder":
        """Enable the HTTP stats listener (``server/stats.py``): JSON
        snapshot at ``/stats``, Prometheus text at ``/metrics``, slow
        traces at ``/traces``. Port 0 binds an ephemeral port (read it
        back from ``.stats.port``). Binds loopback by default — the
        surface is unauthenticated; widen ``host`` deliberately."""
        self._kwargs["stats_port"] = port
        self._kwargs["stats_host"] = host
        return self

    def with_groups(self, groups: int) -> "_Builder":
        """Host N Raft groups (keyspace shards) behind this server —
        docs/SHARDING.md. Default: ``COPYCAT_GROUPS`` (1). Must be
        uniform across the cluster."""
        self._kwargs["groups"] = groups
        return self

    def with_executor(self, executor: str,
                      engine_config: Any | None = None) -> "_Builder":
        """Select the resource executor: ``"cpu"`` (default) or ``"tpu"``
        — the vectorized device engine behind the same resource API
        (SURVEY.md §7.1; mirror of ``withStateMachine``,
        ``AtomixReplica.java:374``). Must be uniform across the cluster."""
        self._kwargs["executor"] = executor
        if engine_config is not None:
            self._kwargs["engine_config"] = engine_config
        return self

    def build(self) -> Any:
        kwargs = dict(self._kwargs)
        if self._cls is AtomixClient:
            kwargs.pop("address", None)
            kwargs.pop("storage", None)
            kwargs.pop("election_timeout", None)
            kwargs.pop("heartbeat_interval", None)
            kwargs.pop("executor", None)
            kwargs.pop("engine_config", None)
            kwargs.pop("stats_port", None)
            kwargs.pop("stats_host", None)
            kwargs.pop("groups", None)
        return self._cls(**kwargs)


class AtomixClient(Atomix):
    """Stateless node: pure client (reference ``AtomixClient.java``)."""

    def __init__(self, members: list[Address], transport: Transport,
                 session_timeout: float = 5.0) -> None:
        super().__init__(RaftClient(members, transport, session_timeout=session_timeout))

    @staticmethod
    def builder(members: list[Address]) -> _Builder:
        return _Builder(AtomixClient, None, members)


class AtomixReplica(Atomix):
    """Stateful node: embedded server + client pinned to it
    (reference ``AtomixReplica.java:45``, ``build():355-379``)."""

    def __init__(
        self,
        address: Address,
        members: list[Address],
        transport: Transport,
        storage: Storage | None = None,
        election_timeout: float = 0.5,
        heartbeat_interval: float = 0.1,
        session_timeout: float = 5.0,
        executor: str = "cpu",
        engine_config: Any | None = None,
        stats_port: int | None = None,
        stats_host: str = "127.0.0.1",
        groups: int | None = None,
    ) -> None:
        machine, groups = _manager_factory(executor, engine_config, groups)
        self.server = RaftServer(
            address, members, transport, machine,
            storage=storage,
            election_timeout=election_timeout, heartbeat_interval=heartbeat_interval,
            session_timeout=session_timeout, groups=groups)
        client = RaftClient(
            list(members), transport, session_timeout=session_timeout,
            connection_strategy=PinnedConnectionStrategy(address))
        super().__init__(client)
        self.address = address
        self._stats_port = stats_port
        self._stats_host = stats_host
        self.stats: Any = None

    @staticmethod
    def builder(address: Address, members: list[Address]) -> _Builder:
        return _Builder(AtomixReplica, address, members)

    async def _do_open(self) -> None:
        # Server first, then the client session (reference AtomixReplica.open).
        self.server.state_machine.prewarm()
        await self.server.open()
        try:
            if self._stats_port is not None:
                from ..server.stats import StatsListener
                self.stats = await StatsListener(
                    self.server, host=self._stats_host,
                    port=self._stats_port).open()
            await self.client.open()
        except BaseException:
            # a failed stats bind / client open must not leak the opened
            # server: Managed never marked US open, so the caller's
            # close() would be a no-op
            if self.stats is not None:
                await self.stats.close()
                self.stats = None
            await self.server.close()
            raise

    async def _do_close(self) -> None:
        self._resources.clear()
        await self.client.close()
        if self.stats is not None:
            await self.stats.close()
            self.stats = None
        await self.server.close()


class AtomixServer(Managed):
    """Standalone server hosting the ResourceManager (no client facade)."""

    def __init__(
        self,
        address: Address,
        members: list[Address],
        transport: Transport,
        storage: Storage | None = None,
        election_timeout: float = 0.5,
        heartbeat_interval: float = 0.1,
        session_timeout: float = 5.0,
        executor: str = "cpu",
        engine_config: Any | None = None,
        stats_port: int | None = None,
        stats_host: str = "127.0.0.1",
        groups: int | None = None,
        state_machine: Any | None = None,
        name: str = "raft",
    ) -> None:
        super().__init__()
        if state_machine is None:
            machine, groups = _manager_factory(executor, engine_config,
                                               groups)
        else:
            # a custom machine (instance or per-group factory) instead
            # of the ResourceManager catalog — what the deployment
            # plane's machine-spec children host (docs/DEPLOYMENT.md);
            # the group count resolves inside RaftServer as usual
            machine = state_machine
        self.server = RaftServer(
            address, members, transport, machine,
            storage=storage,
            election_timeout=election_timeout, heartbeat_interval=heartbeat_interval,
            session_timeout=session_timeout, groups=groups, name=name)
        self.address = address
        self._stats_port = stats_port
        self._stats_host = stats_host
        self.stats: Any = None

    @staticmethod
    def builder(address: Address, members: list[Address]) -> _Builder:
        return _Builder(AtomixServer, address, members)

    async def _do_open(self) -> None:
        prewarm = getattr(self.server.state_machine, "prewarm", None)
        if callable(prewarm):
            prewarm()
        await self.server.open()
        if self._stats_port is not None:
            from ..server.stats import StatsListener
            try:
                self.stats = await StatsListener(
                    self.server, host=self._stats_host,
                    port=self._stats_port).open()
            except BaseException:
                await self.server.close()  # no leaked half-open node
                raise

    async def _do_close(self) -> None:
        if self.stats is not None:
            await self.stats.close()
            self.stats = None
        await self.server.close()

    async def leave(self) -> None:
        await self.server.leave()
