"""Client-side resource virtualization (reference ``InstanceClient.java:35``,
``InstanceSession.java:33``).

``InstanceClient`` implements the RaftClient submit surface but prefixes every
operation with the instance id; ``InstanceSession`` filters the parent
session's events down to this instance (by ``InstanceEvent.resource``).
"""

from __future__ import annotations

from typing import Any, Callable

from ..client.client import ClientSession, RaftClient
from ..protocol.operations import Command, Operation, Query
from ..resource.operations import DeleteCommand
from ..utils.listeners import Listener, Listeners
from .operations import DeleteResource, InstanceCommand, InstanceEvent, InstanceQuery


class InstanceSession:
    """Per-resource view over the parent client session."""

    def __init__(self, instance_id: int, parent: ClientSession) -> None:
        self.id = instance_id
        self.parent = parent
        self._local_listeners: dict[str, Listeners] = {}
        self._parent_listeners: dict[str, Listener] = {}

    @property
    def is_open(self) -> bool:
        return self.parent.is_open

    def on_event(self, event: str, callback: Callable[[Any], Any]) -> Listener:
        listeners = self._local_listeners.get(event)
        if listeners is None:
            listeners = self._local_listeners[event] = Listeners()
            # One parent listener per event name; fans out to local listeners
            # after filtering by instance id (InstanceSession.java handleEvent).
            self._parent_listeners[event] = self.parent.on_event(
                event, lambda message, _e=event: self._handle(_e, message))
        local = listeners.add(callback)
        return local

    def _handle(self, event: str, message: Any) -> None:
        if isinstance(message, InstanceEvent):
            if message.resource != self.id:
                return
            payload = message.message
        else:
            payload = message
        listeners = self._local_listeners.get(event)
        if listeners is not None:
            listeners.accept(payload)

    def publish(self, event: str, message: Any = None) -> None:
        """Local loopback publish: only this node's listeners see it."""
        listeners = self._local_listeners.get(event)
        if listeners is not None:
            listeners.accept(message)

    def on_open(self, callback: Callable[[Any], Any]) -> Listener:
        return self.parent.on_open(callback)

    def on_close(self, callback: Callable[[Any], Any]) -> Listener:
        return self.parent.on_close(callback)

    def close(self) -> None:
        for listener in self._parent_listeners.values():
            listener.close()
        self._parent_listeners.clear()
        self._local_listeners.clear()


class InstanceClient:
    """RaftClient facade routing every op to one resource instance."""

    def __init__(self, instance_id: int, client: RaftClient,
                 on_delete=None) -> None:
        self.instance_id = instance_id
        self.client = client
        self._session = InstanceSession(instance_id, client.session())
        # notifies the owning Atomix facade so its get() singleton cache
        # drops the key — a later get() must create a FRESH resource, not
        # hand back a facade whose server-side instance is gone
        self._on_delete = on_delete

    def session(self) -> InstanceSession:
        return self._session

    def submit_command_nowait(self, operation: Operation) -> Any:
        """Future-returning command submit (the flattened hot path):
        wraps in the instance envelope and stages straight into the
        parent client's micro-batch. Plain commands only — delete
        chaining and queries keep the coroutine path."""
        return self.client.submit_command_nowait(
            InstanceCommand(self.instance_id, operation))

    async def submit(self, operation: Operation) -> Any:
        if isinstance(operation, DeleteCommand):
            # Reference InstanceClient.java:73-75: resource-level delete, then
            # catalog-level DeleteResource.
            result = await self.client.submit(
                InstanceCommand(self.instance_id, operation))
            await self.client.submit(DeleteResource(self.instance_id))
            self._session.close()
            if self._on_delete is not None:
                self._on_delete()
            return result
        if isinstance(operation, Query):
            return await self.client.submit(InstanceQuery(self.instance_id, operation))
        if isinstance(operation, Command):
            return await self.client.submit(InstanceCommand(self.instance_id, operation))
        raise TypeError(f"not an operation: {operation!r}")

    async def close(self) -> None:
        self._session.close()
