"""Resource manager: many logical state machines over ONE replicated log.

The defining architectural move of the reference (SURVEY.md §1): the server
runs a single top-level state machine — :class:`ResourceManager` — that hosts
every resource behind per-resource virtual sessions and executors
(``ResourceManager.java:35``); the client virtualizes with
:class:`InstanceClient`/:class:`InstanceSession` (``InstanceClient.java:35``).
On the TPU engine this multiplexing IS the batch dimension: group g = one
resource's Raft-replicated state machine.
"""

from .operations import (
    CreateResource,
    DeleteResource,
    GetResource,
    InstanceCommand,
    InstanceEvent,
    InstanceQuery,
    KeyOperation,
    ResourceExists,
)
from .state import ManagedResourceSession, ResourceManager
from .instance import InstanceClient, InstanceSession
from .atomix import Atomix, AtomixClient, AtomixReplica, AtomixServer

__all__ = [
    "KeyOperation",
    "GetResource",
    "CreateResource",
    "DeleteResource",
    "ResourceExists",
    "InstanceCommand",
    "InstanceQuery",
    "InstanceEvent",
    "ResourceManager",
    "ManagedResourceSession",
    "InstanceClient",
    "InstanceSession",
    "Atomix",
    "AtomixClient",
    "AtomixReplica",
    "AtomixServer",
]
