"""The Copycat-equivalent operation model and wire protocol.

``operations`` defines ``Command``/``Query`` with the exact consistency and
persistence levels the reference consumes (SURVEY.md §2.3: Command consistency
NONE/SEQUENTIAL/LINEARIZABLE, Query consistency CAUSAL/SEQUENTIAL/
BOUNDED_LINEARIZABLE/LINEARIZABLE, persistence PERSISTENT/EPHEMERAL).

``messages`` defines the client<->server session protocol and the
server<->server Raft RPCs.
"""

from .operations import (
    Command,
    CommandConsistency,
    Operation,
    Persistence,
    Query,
    QueryConsistency,
)
from .messages import (
    AppendRequest,
    AppendResponse,
    CommandRequest,
    CommandResponse,
    JoinRequest,
    JoinResponse,
    KeepAliveRequest,
    KeepAliveResponse,
    LeaveRequest,
    LeaveResponse,
    ProtocolError,
    PublishRequest,
    PublishResponse,
    QueryRequest,
    QueryResponse,
    RegisterRequest,
    RegisterResponse,
    UnregisterRequest,
    UnregisterResponse,
    VoteRequest,
    VoteResponse,
)

__all__ = [
    "Operation",
    "Command",
    "Query",
    "CommandConsistency",
    "QueryConsistency",
    "Persistence",
    "RegisterRequest",
    "RegisterResponse",
    "KeepAliveRequest",
    "KeepAliveResponse",
    "UnregisterRequest",
    "UnregisterResponse",
    "CommandRequest",
    "CommandResponse",
    "QueryRequest",
    "QueryResponse",
    "PublishRequest",
    "PublishResponse",
    "VoteRequest",
    "VoteResponse",
    "AppendRequest",
    "AppendResponse",
    "JoinRequest",
    "JoinResponse",
    "LeaveRequest",
    "LeaveResponse",
    "ProtocolError",
]
