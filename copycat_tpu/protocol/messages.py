"""Wire protocol: client<->server session messages + server<->server Raft RPCs.

Reconstructed from the API the reference consumes from the external Copycat jar
(SURVEY.md §2.3 "Client runtime" / "Session protocol" / "Raft server core").
Serialization ids 200-229 (the reference's op catalogs use 28-127; protocol
messages lived in the external jar, so this block is new).

Every response carries ``error`` (string) — ``NOT_LEADER`` additionally carries
a ``leader`` hint so clients re-route; this is the uniform alternative to
exception marshalling across transports.
"""

from __future__ import annotations

from typing import Any, ClassVar

from ..io.buffer import BufferInput, BufferOutput
from ..io.serializer import Serializer, serialize_with
from ..utils.fields import compile_field_init

# Error codes carried in response.error
NOT_LEADER = "NOT_LEADER"
NO_LEADER = "NO_LEADER"
UNKNOWN_SESSION = "UNKNOWN_SESSION"
INTERNAL = "INTERNAL"
APPLICATION = "APPLICATION"  # state-machine raised; message in error_detail


class ProtocolError(Exception):
    def __init__(self, code: str, detail: str = "", leader: Any = None):
        super().__init__(f"{code}: {detail}" if detail else code)
        self.code = code
        self.detail = detail
        self.leader = leader


class Message:
    """Field-list serialization base: subclasses declare ``_fields``.

    Subclasses that declare ``_fields`` without their own ``__init__``
    get one COMPILED for them (NamedTuple-style): direct attribute
    assignments instead of a per-field ``kwargs.get`` + ``setattr``
    loop. Messages are constructed per op on the session hot path, so
    the generic loop was a measured share of the SPI plane's per-op
    cost (PERF.md round 6).

    ``_optional`` marks that many TRAILING fields as wire-optional: a
    trailing run of ``None`` values is omitted from the encoding, and a
    reader that runs out of buffer fills the rest with ``None``. That
    makes a new trailing field (the tracing plane's ``trace``) free on
    the wire when unused — frames stay byte-identical to the
    pre-tracing schema (the golden differential in
    tests/test_trace_plane.py). The omission is only decodable when the
    message ends its buffer, so optional fields are restricted to
    TOP-LEVEL RPC messages (one frame = one message); never mark a
    message that nests inside another object graph."""

    _fields: ClassVar[tuple[str, ...]] = ()
    _optional: ClassVar[int] = 0

    def __init__(self, **kwargs: Any) -> None:
        for name in self._fields:
            setattr(self, name, kwargs.get(name))

    def __init_subclass__(cls, **kwargs: Any) -> None:
        super().__init_subclass__(**kwargs)
        fields = cls.__dict__.get("_fields")
        if fields is None or "__init__" in cls.__dict__:
            return
        compile_field_init(cls, fields)

    def write_object(self, buf: BufferOutput, serializer: Serializer) -> None:
        fields = self._fields
        n = len(fields)
        opt = self._optional
        while opt and getattr(self, fields[n - 1]) is None:
            n -= 1
            opt -= 1
        for name in fields[:n]:
            serializer.write_object(getattr(self, name), buf)

    def read_object(self, buf: BufferInput, serializer: Serializer) -> None:
        fields = self._fields
        required = len(fields) - self._optional
        for i, name in enumerate(fields):
            if i >= required and buf.remaining == 0:
                setattr(self, name, None)
            else:
                setattr(self, name, serializer.read_object(buf))

    def __repr__(self) -> str:
        inner = ", ".join(f"{n}={getattr(self, n)!r}" for n in self._fields)
        return f"{type(self).__name__}({inner})"


# Marker read by serialize_with: classes inheriting these exact function
# objects serialize as a plain field list, which the native codec
# (io/codec.py) can walk entirely in C.
Message.write_object._generic_fields = True
Message.read_object._generic_fields = True


class Response(Message):
    """Base response: ``error`` is an error code, ``leader`` a routing hint."""

    @property
    def ok(self) -> bool:
        return not getattr(self, "error", None)

    def raise_if_error(self) -> "Response":
        error = getattr(self, "error", None)
        if error:
            raise ProtocolError(error, getattr(self, "error_detail", "") or "",
                                getattr(self, "leader", None))
        return self


# ---------------------------------------------------------------------------
# Client <-> server session protocol
# ---------------------------------------------------------------------------


@serialize_with(200)
class RegisterRequest(Message):
    _fields = ("client_id", "timeout")


@serialize_with(201)
class RegisterResponse(Response):
    # session_id doubles as the registering entry's log index (stamped
    # with the group count on a multi-group server — docs/SHARDING.md).
    # groups: the server's Raft group count; >1 switches the client into
    # multi-group mode (per-group read indices + event channels).
    _fields = ("error", "error_detail", "leader", "session_id", "timeout",
               "members", "groups")


@serialize_with(202)
class KeepAliveRequest(Message):
    # command_seq: highest command sequence the client has a response for.
    # event_index: highest event index the client has processed.
    # unsubscribe (optional trailing, omitted when None): instance ids
    # whose edge subscriptions (docs/EDGE_READS.md) the client dropped
    # (LRU eviction) — the serving member retires them from its
    # subscriber registry. Member-local, never replicated.
    _fields = ("session_id", "command_seq", "event_index", "unsubscribe")
    _optional = 1


@serialize_with(203)
class KeepAliveResponse(Response):
    _fields = ("error", "error_detail", "leader", "members")


@serialize_with(204)
class UnregisterRequest(Message):
    _fields = ("session_id",)


@serialize_with(205)
class UnregisterResponse(Response):
    _fields = ("error", "error_detail", "leader")


@serialize_with(206)
class CommandRequest(Message):
    # seq: client-assigned sequence for exactly-once application.
    # trace: per-request trace id (utils/tracing.py) — None when tracing
    # is disabled; a non-None id asks the server to record spans for it.
    _fields = ("session_id", "seq", "operation", "trace")


@serialize_with(207)
class CommandResponse(Response):
    # index: log index at which the command applied (the linearization point).
    # event_index: highest event index published to this session at the time.
    _fields = ("error", "error_detail", "leader", "index", "event_index", "result")


@serialize_with(208)
class QueryRequest(Message):
    # index: client's high-water commit index for SEQUENTIAL/CAUSAL reads.
    # subscribe (optional trailing, omitted when None): truthy asks the
    # serving member to register this session as an edge-delta
    # subscriber for the resources the read touches and seed the reply's
    # ``edge`` field (docs/EDGE_READS.md); unsubscribed planes stay
    # byte-identical.
    _fields = ("session_id", "index", "operation", "consistency",
               "subscribe")
    _optional = 1


@serialize_with(209)
class QueryResponse(Response):
    # edge (optional trailing, omitted when None): edge replica seeds
    # ``[(instance_id, version, state), ...]`` answering a subscribing
    # read (docs/EDGE_READS.md).
    _fields = ("error", "error_detail", "leader", "index", "result",
               "edge")
    _optional = 1


@serialize_with(224)
class CommandBatchRequest(Message):
    """Micro-batched commands: one transport message carrying many
    sequenced commands from one session (the client's same-turn submits
    coalesce; the reference's per-command RPC framing pays per-message
    overhead the batch amortizes). ``entries`` = [(seq, operation), ...]
    in seq order. ``trace`` as on CommandRequest (one id per batch)."""

    _fields = ("session_id", "entries", "trace")


@serialize_with(225)
class CommandBatchResponse(Response):
    """Per-command outcomes: ``entries`` = [(seq, index, result,
    error_code, error_detail), ...]; ``event_index`` as CommandResponse."""

    _fields = ("error", "error_detail", "leader", "event_index", "entries")


@serialize_with(226)
class QueryBatchRequest(Message):
    """Micro-batched reads of ONE consistency level: the server performs
    the consistency gate (leadership confirmation / applied-index wait)
    once for the whole batch — for LINEARIZABLE reads that amortizes a
    quorum round over N queries. ``operations`` positional.
    ``subscribe`` as on QueryRequest (optional trailing)."""

    _fields = ("session_id", "index", "consistency", "operations",
               "subscribe")
    _optional = 1


@serialize_with(227)
class QueryBatchResponse(Response):
    """``entries`` positional with the request: [(result, error_code,
    error_detail), ...]. ``edge`` as on QueryResponse (optional
    trailing)."""

    _fields = ("error", "error_detail", "leader", "index", "entries",
               "edge")
    _optional = 1


@serialize_with(210)
class PublishRequest(Message):
    """Server -> client event push (session event channel).

    ``events`` is a list of (event_name, payload) applied at ``index``;
    ``prev_event_index`` lets the client detect gaps and request a replay via
    keep-alive acks.

    ``group`` scopes the event channel on a multi-group server: each
    group's replica of a session numbers its own event stream, and the
    client tracks ``event_index`` per group (None = single-group, the
    legacy scalar channel).

    ``trace`` (optional trailing, omitted when None): the trace id of
    the applied command whose events this push delivers, so the client
    records a ``client.event`` span on the same causal timeline.

    ``deltas`` (optional trailing, omitted when None): edge state
    deltas ``[(instance_id, version, state), ...]`` for resources this
    session subscribed to (docs/EDGE_READS.md). Deltas are join-
    semilattice merges client-side (max version wins), so they need no
    position in the event channel's gap/replay machinery: a delta-only
    push carries ``event_index=None`` and the client acks its current
    position untouched. ``state=None`` retires the replica entry (the
    resource was deleted or stopped being edge-servable).
    """

    _fields = ("session_id", "event_index", "prev_event_index", "events",
               "group", "trace", "deltas")
    _optional = 2


@serialize_with(211)
class PublishResponse(Response):
    _fields = ("error", "error_detail", "event_index")


# ---------------------------------------------------------------------------
# Server <-> server Raft RPCs
# ---------------------------------------------------------------------------


@serialize_with(216)
class VoteRequest(Message):
    # group: the Raft group this RPC belongs to on a multi-group server
    # (docs/SHARDING.md); None = the single-group plane, byte-identical
    # to the pre-sharding wire shape. Same field on Append/Install.
    _fields = ("term", "candidate", "last_log_index", "last_log_term",
               "group")


@serialize_with(217)
class VoteResponse(Response):
    _fields = ("error", "error_detail", "term", "voted")


@serialize_with(218)
class AppendRequest(Message):
    # global_index: minimum replicated index across all members — followers may
    # compact cleaned entries up to it (SURVEY.md §5.4 compaction contract).
    # fill_to: end of the index window this append covers; entries omitted from
    # the window were cleaned+compacted (effects superseded) — the follower
    # gap-fills those slots and never applies them, mirroring the reference's
    # replay-after-compaction semantics.
    # trace: optional trailing (omitted when None — the untraced wire is
    # byte-identical to the pre-tracing schema): ``(trace id, entry
    # index)`` when this window carries a traced entry to quorum, so the
    # follower records its ingest+fsync span under the same causal
    # timeline and marks the entry for event-push attribution
    # (docs/OBSERVABILITY.md "Cluster-wide causal tracing").
    _fields = ("term", "leader", "prev_index", "prev_term", "entries", "commit_index",
               "global_index", "fill_to", "group", "trace")
    _optional = 1


@serialize_with(219)
class AppendResponse(Response):
    # last_index: follower's last log index after the append (for next_index
    # fast rewind on failure).
    _fields = ("error", "error_detail", "term", "success", "last_index")


@serialize_with(212)
class InstallRequest(Message):
    """Leader -> follower snapshot-install stream (docs/DURABILITY.md).

    Sent when a follower's ``next_index`` has fallen behind the leader's
    prefix-truncated log: the newest snapshot's payload is chunked and
    streamed over the peer connection's correlated multiplexing (up to
    the replication pipeline's depth of chunks in flight).  ``index`` is
    the snapshot's applied index, ``snap_term`` the term of the entry at
    that index (the follower's log restarts just past it), ``total`` the
    full payload length in bytes, ``offset`` this chunk's byte position,
    ``data`` the chunk, and ``done`` marks the final (empty) frame that
    asks the follower to assemble + restore.
    """

    _fields = ("term", "leader", "index", "snap_term", "total", "offset",
               "data", "done", "group")


@serialize_with(213)
class InstallResponse(Response):
    # offset: chunk acks echo the chunk's offset; a failed final assembly
    # reports the first missing byte offset as a diagnostic. The leader's
    # retry contract is WHOLE-RETRY (the follower clears its assembly
    # buffer on failure) — offset is informational, not a resume cursor.
    # last_index: the follower's log tail after a completed install.
    _fields = ("error", "error_detail", "term", "success", "offset",
               "last_index")


@serialize_with(228)
class ProxyRequest(Message):
    """Server -> server ingress forwarding on a multi-group server
    (docs/SHARDING.md): the member holding a client's connection routes
    each staged sub-request to the owning group's leader. ``kind`` names
    the staging entry point (``register`` / ``keepalive`` /
    ``unregister`` / ``commands`` / ``query``); ``payload`` is the
    kind-specific tuple. Responses travel as :class:`ProxyResponse` with
    the kind-specific ``result`` payload, plus the uniform
    error/leader-hint fields so the ingress can retry toward the
    group's current leader.

    ``trace`` (optional trailing on both directions, omitted when
    None): the originating trace id — the owning group's leader records
    its append/quorum/apply spans under it, and the response echoes it
    so the hop stays correlated even when responses are inspected off
    the connection's multiplexing.
    """

    _fields = ("group", "kind", "payload", "trace")
    _optional = 1


@serialize_with(229)
class ProxyResponse(Response):
    _fields = ("error", "error_detail", "leader", "result", "trace")
    _optional = 1


@serialize_with(220)
class JoinRequest(Message):
    _fields = ("member",)


@serialize_with(221)
class JoinResponse(Response):
    _fields = ("error", "error_detail", "leader", "members")


@serialize_with(222)
class LeaveRequest(Message):
    _fields = ("member",)


@serialize_with(223)
class LeaveResponse(Response):
    _fields = ("error", "error_detail", "leader", "members")
