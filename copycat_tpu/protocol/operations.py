"""Operation model (Copycat ``Operation``/``Command``/``Query`` equivalent).

Levels mirror the reference exactly (consumed at ``Consistency.java:60-176``):

- Command consistency: ``NONE`` (complete on commit, events async),
  ``SEQUENTIAL`` (events sequentially consistent), ``LINEARIZABLE`` (events
  reach subscribers before the command response completes).
- Query consistency: ``CAUSAL``, ``SEQUENTIAL``, ``BOUNDED_LINEARIZABLE``
  (leader lease), ``LINEARIZABLE`` (leader confirms with a quorum round).
- Persistence: ``PERSISTENT`` (tombstone — must survive until explicitly
  cleaned) vs ``EPHEMERAL`` (droppable once superseded), the log-compaction
  contract every reference state machine is written against (SURVEY.md §5.4).
"""

from __future__ import annotations

import enum


class CommandConsistency(enum.Enum):
    NONE = "none"
    SEQUENTIAL = "sequential"
    LINEARIZABLE = "linearizable"


class QueryConsistency(enum.Enum):
    CAUSAL = "causal"
    SEQUENTIAL = "sequential"
    BOUNDED_LINEARIZABLE = "bounded_linearizable"
    LINEARIZABLE = "linearizable"


class Persistence(enum.Enum):
    # PERSISTENT entries are tombstones: compaction must retain them until the
    # state machine cleans them. EPHEMERAL entries may be dropped as soon as
    # they are applied on all servers and superseded.
    PERSISTENT = "persistent"
    EPHEMERAL = "ephemeral"


class Operation:
    """Base class for all replicated operations (serializable)."""

    __slots__ = ()


class Command(Operation):
    """A state-mutating operation, replicated through the log."""

    __slots__ = ()

    def consistency(self) -> CommandConsistency:
        return CommandConsistency.LINEARIZABLE

    def persistence(self) -> Persistence:
        return Persistence.PERSISTENT


class Query(Operation):
    """A read-only operation, served outside the log per its consistency."""

    __slots__ = ()

    def consistency(self) -> QueryConsistency:
        return QueryConsistency.LINEARIZABLE
