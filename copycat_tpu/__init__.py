"""copycat_tpu — a TPU-native distributed coordination framework.

A from-scratch rebuild of the capabilities of Atomix/Copycat (reference:
``/root/reference``, Atomix 0.1.0-SNAPSHOT on Copycat Raft): Raft-replicated,
session-based distributed resources — atomic values/counters, maps, multimaps,
sets, queues, locks, leader elections, group membership, topics, a message bus —
behind an async client API with per-operation consistency levels.

Architecture (see SURVEY.md in the repo root):

- ``utils/ io/`` — the Catalyst-equivalent substrate: serialization with a
  type-id registry, pluggable async transports (in-memory Local + TCP),
  lifecycle/listener utilities.
- ``protocol/ server/ client/`` — the Copycat-equivalent Raft core, written as
  a pure-Python CPU oracle: leader election, log replication, commitment,
  linearizable sessions with server-push events, log cleaning/compaction.
- ``resource/ manager/`` — the Atomix-equivalent resource layer: many logical
  state machines multiplexed over one replicated log.
- ``atomic/ collections/ coordination/`` — the resource library.
- ``ops/ models/ parallel/`` — the TPU-native consensus engine: all Raft groups
  batched into fixed-shape ``[num_groups, num_peers]`` tensors, stepped as one
  XLA program (quorum tallies via sums/psums over the peer axis, state-machine
  apply via vectorized kernels), sharded over a ``jax.sharding.Mesh``.
"""

__version__ = "0.4.0"

# Honor an explicit JAX_PLATFORMS env var BEFORE any backend can
# initialize: accelerator plugin site config overrides the env var via
# jax.config, and a plugin dialing a dead accelerator hangs device
# enumeration forever — a user running any entry point (example, script,
# server) with JAX_PLATFORMS=cpu must actually get the CPU backend.
# (Round-3 post-mortem; same pin as tests/conftest.py and
# __graft_entry__.dryrun_multichip.) No-op when the env var is unset,
# and jax is only imported here when it is set.
import os as _os

if _os.environ.get("JAX_PLATFORMS"):
    from .utils.platform import honor_jax_platforms_env as _honor

    _honor()
    del _honor
del _os
