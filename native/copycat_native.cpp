// Native I/O substrate: epoll event loop + frame codec.
//
// Fills the reference's NettyTransport role (SURVEY.md §5.8, L0 I/O
// substrate) as real native runtime code: one epoll thread owns all
// sockets, parses the shared wire format
//     [u32 length][u8 kind][u64 correlation id][payload]
// (identical to copycat_tpu/io/tcp.py, so native and asyncio endpoints
// interoperate), and hands complete frames to Python through a
// mutex+condvar event queue polled via cn_poll. Sends are enqueued from
// any thread and flushed by the loop (eventfd wakeup).
//
// Connections are identified by a monotonically increasing conn id, never
// by raw fd: the kernel reuses fd numbers immediately, so a stale
// ETYPE_CLOSE routed by fd could hit a new connection. The id rides in
// epoll_event.data.u64.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the image).

#include <arpa/inet.h>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <map>
#include <mutex>
#include <condition_variable>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <string>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>
#include <vector>

namespace {

constexpr int ETYPE_ACCEPT = 1;
constexpr int ETYPE_FRAME = 2;
constexpr int ETYPE_CLOSE = 3;
constexpr int ETYPE_CONNECT = 4;  // nonblocking connect completed ok
constexpr size_t HEADER = 4 + 1 + 8;
constexpr size_t MAX_FRAME = 64 * 1024 * 1024;
constexpr uint64_t WAKE_ID = 0;  // reserved conn id for the wake eventfd

struct Event {
  int conn;
  int etype;
  uint8_t kind;
  uint64_t corr;
  std::vector<uint8_t> payload;
};

struct Conn {
  int id = -1;
  int fd = -1;
  bool listener = false;
  bool connecting = false;  // nonblocking connect not yet completed
  std::vector<uint8_t> rbuf;
  std::deque<std::vector<uint8_t>> wq;  // pending encoded frames
  size_t wq_off = 0;                    // offset into wq.front()
};

struct Loop {
  int epfd = -1;
  int wakefd = -1;
  pthread_t thread{};
  std::atomic<bool> running{false};

  std::mutex mu;                 // guards conns / cmds / next_id
  int next_id = 1;               // 0 reserved for the wake fd
  std::map<int, Conn> conns;     // conn id -> state
  std::deque<std::pair<int, std::vector<uint8_t>>> cmds;  // (id, frame)
  std::deque<int> closing;

  std::mutex evmu;
  std::condition_variable evcv;
  std::deque<Event> events;

  void push_event(Event&& e) {
    {
      std::lock_guard<std::mutex> g(evmu);
      events.push_back(std::move(e));
    }
    evcv.notify_one();
  }
  void wake() const {
    uint64_t one = 1;
    ssize_t r = write(wakefd, &one, sizeof(one));
    (void)r;
  }
};

void set_nonblock(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

// Resolve host (name or numeric) to an IPv4 sockaddr; empty host maps to
// INADDR_ANY for listeners and loopback for connects.
bool resolve_ipv4(const char* host, int port, bool passive,
                  sockaddr_in* out) {
  std::memset(out, 0, sizeof(*out));
  out->sin_family = AF_INET;
  out->sin_port = htons(uint16_t(port));
  if (!host || !*host) {
    out->sin_addr.s_addr = passive ? htonl(INADDR_ANY)
                                   : htonl(INADDR_LOOPBACK);
    return true;
  }
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  if (passive) hints.ai_flags = AI_PASSIVE;
  addrinfo* res = nullptr;
  if (getaddrinfo(host, nullptr, &hints, &res) != 0 || res == nullptr)
    return false;
  out->sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
  freeaddrinfo(res);
  return true;
}

void epoll_update(Loop* l, const Conn& c, bool want_write) {
  epoll_event ev{};
  ev.events = EPOLLIN | (want_write ? EPOLLOUT : 0u);
  ev.data.u64 = uint64_t(c.id);
  epoll_ctl(l->epfd, EPOLL_CTL_MOD, c.fd, &ev);
}

void epoll_add(Loop* l, const Conn& c, bool want_write) {
  epoll_event ev{};
  ev.events = EPOLLIN | (want_write ? EPOLLOUT : 0u);
  ev.data.u64 = uint64_t(c.id);
  epoll_ctl(l->epfd, EPOLL_CTL_ADD, c.fd, &ev);
}

void close_conn_locked(Loop* l, int id, bool emit) {
  auto it = l->conns.find(id);
  if (it == l->conns.end()) return;
  epoll_ctl(l->epfd, EPOLL_CTL_DEL, it->second.fd, nullptr);
  close(it->second.fd);
  bool listener = it->second.listener;
  l->conns.erase(it);
  if (emit && !listener)
    l->push_event(Event{id, ETYPE_CLOSE, 0, 0, {}});
}

// parse complete frames out of c->rbuf
void drain_frames(Loop* l, Conn* c) {
  size_t off = 0;
  while (c->rbuf.size() - off >= HEADER) {
    const uint8_t* p = c->rbuf.data() + off;
    uint32_t len = (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
                   (uint32_t(p[2]) << 8) | uint32_t(p[3]);
    if (len > MAX_FRAME) {  // poisoned stream: drop the connection
      close_conn_locked(l, c->id, true);
      return;
    }
    if (c->rbuf.size() - off < HEADER + len) break;
    uint8_t kind = p[4];
    uint64_t corr = 0;
    for (int i = 0; i < 8; i++) corr = (corr << 8) | p[5 + i];
    Event e{c->id, ETYPE_FRAME, kind, corr, {}};
    e.payload.assign(p + HEADER, p + HEADER + len);
    l->push_event(std::move(e));
    off += HEADER + len;
  }
  if (off > 0) c->rbuf.erase(c->rbuf.begin(), c->rbuf.begin() + off);
}

void handle_readable(Loop* l, int id) {
  auto it = l->conns.find(id);
  if (it == l->conns.end()) return;
  Conn& c = it->second;
  if (c.listener) {
    for (;;) {
      int cfd = accept(c.fd, nullptr, nullptr);
      if (cfd < 0) break;
      set_nonblock(cfd);
      int one = 1;
      setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      Conn nc;
      nc.id = l->next_id++;
      nc.fd = cfd;
      epoll_add(l, nc, false);
      int nid = nc.id;
      l->conns.emplace(nid, std::move(nc));
      // corr carries the listener's conn id so Python can route the accept
      l->push_event(Event{nid, ETYPE_ACCEPT, 0, uint64_t(id), {}});
    }
    return;
  }
  char buf[65536];
  for (;;) {
    ssize_t n = recv(c.fd, buf, sizeof(buf), 0);
    if (n > 0) {
      c.rbuf.insert(c.rbuf.end(), buf, buf + n);
      if (c.rbuf.size() >= HEADER) drain_frames(l, &c);
      if (l->conns.find(id) == l->conns.end()) return;  // dropped mid-parse
    } else if (n == 0) {
      close_conn_locked(l, id, true);
      return;
    } else {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      if (errno == EINTR) continue;
      close_conn_locked(l, id, true);
      return;
    }
  }
}

void handle_writable(Loop* l, int id) {
  auto it = l->conns.find(id);
  if (it == l->conns.end()) return;
  Conn& c = it->second;
  if (c.connecting) {  // nonblocking connect completed (or failed)
    int err = 0;
    socklen_t len = sizeof(err);
    getsockopt(c.fd, SOL_SOCKET, SO_ERROR, &err, &len);
    if (err != 0) {
      close_conn_locked(l, id, true);
      return;
    }
    c.connecting = false;
    int one = 1;
    setsockopt(c.fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    l->push_event(Event{id, ETYPE_CONNECT, 0, 0, {}});
  }
  while (!c.wq.empty()) {
    auto& front = c.wq.front();
    ssize_t n = send(c.fd, front.data() + c.wq_off, front.size() - c.wq_off,
                     MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) return;
      if (errno == EINTR) continue;
      close_conn_locked(l, id, true);
      return;
    }
    c.wq_off += size_t(n);
    if (c.wq_off == front.size()) {
      c.wq.pop_front();
      c.wq_off = 0;
    }
  }
  epoll_update(l, c, false);
}

void* loop_main(void* arg) {
  Loop* l = static_cast<Loop*>(arg);
  epoll_event evs[128];
  while (l->running.load(std::memory_order_acquire)) {
    int n = epoll_wait(l->epfd, evs, 128, 200);
    std::lock_guard<std::mutex> g(l->mu);
    for (int i = 0; i < n; i++) {
      uint64_t id64 = evs[i].data.u64;
      if (id64 == WAKE_ID) {
        uint64_t tmp;
        ssize_t r = read(l->wakefd, &tmp, sizeof(tmp));
        (void)r;
        continue;
      }
      int id = int(id64);
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        close_conn_locked(l, id, true);
        continue;
      }
      if (evs[i].events & EPOLLIN) handle_readable(l, id);
      if (evs[i].events & EPOLLOUT) handle_writable(l, id);
    }
    // drain queued sends and closes from other threads
    while (!l->cmds.empty()) {
      auto [id, frame] = std::move(l->cmds.front());
      l->cmds.pop_front();
      auto it = l->conns.find(id);
      if (it == l->conns.end()) continue;
      it->second.wq.push_back(std::move(frame));
      epoll_update(l, it->second, true);
      if (!it->second.connecting) handle_writable(l, id);
    }
    while (!l->closing.empty()) {
      int id = l->closing.front();
      l->closing.pop_front();
      close_conn_locked(l, id, false);
    }
  }
  return nullptr;
}

}  // namespace

extern "C" {

void* cn_new() {
  Loop* l = new Loop();
  l->epfd = epoll_create1(0);
  l->wakefd = eventfd(0, EFD_NONBLOCK);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = WAKE_ID;
  epoll_ctl(l->epfd, EPOLL_CTL_ADD, l->wakefd, &ev);
  return l;
}

int cn_start(void* h) {
  Loop* l = static_cast<Loop*>(h);
  l->running.store(true, std::memory_order_release);
  return pthread_create(&l->thread, nullptr, loop_main, l) == 0 ? 0 : -1;
}

int cn_listen(void* h, const char* host, int port) {
  Loop* l = static_cast<Loop*>(h);
  sockaddr_in addr;
  if (!resolve_ipv4(host, port, /*passive=*/true, &addr)) return -1;
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      listen(fd, 128) < 0) {
    close(fd);
    return -1;
  }
  set_nonblock(fd);
  std::lock_guard<std::mutex> g(l->mu);
  Conn c;
  c.id = l->next_id++;
  c.fd = fd;
  c.listener = true;
  epoll_add(l, c, false);
  int id = c.id;
  l->conns.emplace(id, std::move(c));
  return id;
}

int cn_connect(void* h, const char* host, int port) {
  Loop* l = static_cast<Loop*>(h);
  sockaddr_in addr;
  if (!resolve_ipv4(host, port, /*passive=*/false, &addr)) return -1;
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  set_nonblock(fd);
  bool pending = false;
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    if (errno == EINPROGRESS) {
      pending = true;  // completion (or failure) delivered via EPOLLOUT
    } else {
      close(fd);
      return -1;
    }
  }
  if (!pending) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  }
  std::lock_guard<std::mutex> g(l->mu);
  Conn c;
  c.id = l->next_id++;
  c.fd = fd;
  c.connecting = pending;
  epoll_add(l, c, pending);
  int id = c.id;
  l->conns.emplace(id, std::move(c));
  if (!pending) l->push_event(Event{id, ETYPE_CONNECT, 0, 0, {}});
  return id;
}

int cn_send(void* h, int conn, uint8_t kind, uint64_t corr,
            const uint8_t* data, int len) {
  Loop* l = static_cast<Loop*>(h);
  std::vector<uint8_t> frame(HEADER + size_t(len));
  frame[0] = uint8_t(len >> 24);
  frame[1] = uint8_t(len >> 16);
  frame[2] = uint8_t(len >> 8);
  frame[3] = uint8_t(len);
  frame[4] = kind;
  for (int i = 0; i < 8; i++)
    frame[5 + i] = uint8_t(corr >> (8 * (7 - i)));
  if (len > 0) memcpy(frame.data() + HEADER, data, size_t(len));
  {
    std::lock_guard<std::mutex> g(l->mu);
    if (l->conns.find(conn) == l->conns.end()) return -1;
    l->cmds.emplace_back(conn, std::move(frame));
  }
  l->wake();
  return 0;
}

// Returns payload length (>=0) with out params filled, -1 on timeout.
int cn_poll(void* h, int timeout_ms, int* conn, int* etype, uint8_t* kind,
            uint64_t* corr, uint8_t* buf, int cap) {
  Loop* l = static_cast<Loop*>(h);
  std::unique_lock<std::mutex> g(l->evmu);
  if (l->events.empty()) {
    l->evcv.wait_for(g, std::chrono::milliseconds(timeout_ms),
                     [l] { return !l->events.empty(); });
  }
  if (l->events.empty()) return -1;
  int n = int(l->events.front().payload.size());
  if (n > cap) {  // caller must re-poll with a bigger buffer; keep event
    *conn = l->events.front().conn;
    *etype = 0;
    *kind = 0;
    *corr = uint64_t(n);
    return -2;
  }
  Event e = std::move(l->events.front());
  l->events.pop_front();
  g.unlock();
  *conn = e.conn;
  *etype = e.etype;
  *kind = e.kind;
  *corr = e.corr;
  if (n > 0) memcpy(buf, e.payload.data(), size_t(n));
  return n;
}

int cn_close_conn(void* h, int conn) {
  Loop* l = static_cast<Loop*>(h);
  {
    std::lock_guard<std::mutex> g(l->mu);
    l->closing.push_back(conn);
  }
  l->wake();
  return 0;
}

void cn_shutdown(void* h) {
  Loop* l = static_cast<Loop*>(h);
  l->running.store(false, std::memory_order_release);
  l->wake();
  pthread_join(l->thread, nullptr);
  for (auto& [id, c] : l->conns) close(c.fd);
  close(l->epfd);
  close(l->wakefd);
  delete l;
}

}  // extern "C"
