/* Native wire codec: the Catalyst-serializer object graph in C.
 *
 * Byte-identical to copycat_tpu/io/serializer.py (the pure-Python
 * reference implementation and fallback): zigzag-LEB128 varints,
 * big-endian f64, tagged primitives/containers, registered types as
 * tag 16+id. Generic field-list classes (protocol.messages.Message
 * subclasses — the whole session/RPC hot path) are walked entirely in
 * C; classes with hand-written write_object/read_object round-trip
 * through Python callbacks registered at configure() time.
 *
 * Anything the C path cannot express raises Fallback, and
 * Serializer.write/read re-runs the pure-Python codec — the native
 * path is an accelerator, never a semantic fork.
 *
 * Reference framing: the reference's serializer is the external
 * Catalyst jar running on the JVM's JIT; this is the equivalent
 * native runtime component (SURVEY.md section 2.3 "serialization").
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <string.h>

/* wire tags (serializer.py) */
#define T_NULL 0
#define T_TRUE 1
#define T_FALSE 2
#define T_INT 3
#define T_FLOAT 4
#define T_STR 5
#define T_BYTES 6
#define T_LIST 7
#define T_DICT 8
#define T_TUPLE 9
#define T_SET 10
#define T_CLASS 11

/* module state: live dicts owned by serializer.py + callbacks */
static PyObject *g_id_by_type;   /* dict: type -> int */
static PyObject *g_type_by_id;   /* dict: int -> type */
static PyObject *g_fields_by_id; /* dict: int -> tuple[str] | None */
static PyObject *g_optional_by_id; /* dict: int -> int (trailing optional) */
static PyObject *g_encode_body;  /* callable(obj) -> bytes (custom types) */
static PyObject *g_decode_body;  /* callable(cls, bytes, pos) -> (obj, pos) */
static PyObject *g_fallback;     /* exception type */
static PyObject *g_empty_args;   /* cached () for direct tp_new calls */

/* ------------------------------------------------------------------ */
/* writer                                                              */

typedef struct {
    unsigned char *buf;
    Py_ssize_t len, cap;
} Writer;

static int w_reserve(Writer *w, Py_ssize_t extra) {
    if (w->len + extra <= w->cap) return 0;
    Py_ssize_t cap = w->cap ? w->cap : 256;
    while (cap < w->len + extra) cap *= 2;
    unsigned char *nb = PyMem_Realloc(w->buf, cap);
    if (!nb) { PyErr_NoMemory(); return -1; }
    w->buf = nb;
    w->cap = cap;
    return 0;
}

static int w_raw(Writer *w, const void *p, Py_ssize_t n) {
    if (w_reserve(w, n) < 0) return -1;
    memcpy(w->buf + w->len, p, n);
    w->len += n;
    return 0;
}

/* LEB128 of an already-zigzagged value */
static int w_uvarint(Writer *w, unsigned long long zz) {
    if (w_reserve(w, 10) < 0) return -1;
    while (zz >= 0x80) {
        w->buf[w->len++] = (unsigned char)(zz & 0x7F) | 0x80;
        zz >>= 7;
    }
    w->buf[w->len++] = (unsigned char)zz;
    return 0;
}

static int w_varint(Writer *w, long long v) {
    unsigned long long zz =
        ((unsigned long long)v << 1) ^ (unsigned long long)(v >> 63);
    return w_uvarint(w, zz);
}

static int w_f64(Writer *w, double d) {
    union { double d; unsigned long long u; } x;
    x.d = d;
    unsigned char be[8];
    for (int i = 0; i < 8; i++) be[i] = (unsigned char)(x.u >> (56 - 8 * i));
    return w_raw(w, be, 8);
}

/* ------------------------------------------------------------------ */
/* reader                                                              */

typedef struct {
    const unsigned char *data;
    Py_ssize_t len, pos;
    PyObject *source; /* bytes object backing `data` (borrowed) */
} Reader;

static int r_need(Reader *r, Py_ssize_t n) {
    /* `pos + n` could overflow for a crafted length varint — compare
     * against the remaining bytes instead (r->len - r->pos never
     * overflows); reject negative n here too, belt and braces */
    if (n < 0 || n > r->len - r->pos) {
        PyErr_Format(PyExc_EOFError, "buffer underflow: need %zd at %zd/%zd",
                     n, r->pos, r->len);
        return -1;
    }
    return 0;
}

/* returns 0 on success; *out = decoded (un-zigzagged) value. Overflowing
 * 64 zigzag bits raises Fallback (arbitrary-precision ints take the
 * pure-Python path). */
static int r_varint(Reader *r, long long *out) {
    unsigned long long zz = 0;
    int shift = 0;
    for (;;) {
        if (r_need(r, 1) < 0) return -1;
        unsigned char b = r->data[r->pos++];
        unsigned long long chunk = b & 0x7F;
        if (shift > 63 || (shift == 63 && chunk > 1)) {
            PyErr_SetString(g_fallback, "varint exceeds 64 bits");
            return -1;
        }
        zz |= chunk << shift;
        if (!(b & 0x80)) break;
        shift += 7;
    }
    *out = (long long)(zz >> 1) ^ -(long long)(zz & 1);
    return 0;
}

static int r_f64(Reader *r, double *out) {
    if (r_need(r, 8) < 0) return -1;
    unsigned long long u = 0;
    for (int i = 0; i < 8; i++) u = (u << 8) | r->data[r->pos++];
    union { double d; unsigned long long u; } x;
    x.u = u;
    *out = x.d;
    return 0;
}

/* ------------------------------------------------------------------ */
/* encode                                                              */

static int enc(PyObject *obj, Writer *w, int depth);

/* matches Python's recursion limit semantics: deeper graphs fall
 * back to the pure-Python codec, which raises RecursionError
 * cleanly instead of overflowing the C stack (fuzz finding) */
#define MAX_DEPTH 1000

static int enc_seq_items(PyObject *fast, Writer *w, int depth) {
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    for (Py_ssize_t i = 0; i < n; i++) {
        if (enc(PySequence_Fast_GET_ITEM(fast, i), w, depth) < 0) return -1;
    }
    return 0;
}

static int enc_registered(PyObject *obj, Writer *w, int depth) {
    PyObject *type = (PyObject *)Py_TYPE(obj);
    PyObject *idobj = PyDict_GetItemWithError(g_id_by_type, type);
    if (!idobj) {
        if (!PyErr_Occurred())
            PyErr_Format(g_fallback, "unregistered type %s",
                         Py_TYPE(obj)->tp_name);
        return -1;
    }
    long long tid = PyLong_AsLongLong(idobj);
    if (tid < 0 && PyErr_Occurred()) return -1;
    if (w_varint(w, 16 + tid) < 0) return -1;
    PyObject *fields = PyDict_GetItemWithError(g_fields_by_id, idobj);
    if (!fields) {
        if (PyErr_Occurred()) return -1;
        PyErr_Format(g_fallback, "no codec meta for id %lld", tid);
        return -1;
    }
    if (fields == Py_None) { /* custom write_object via Python */
        PyObject *body = PyObject_CallOneArg(g_encode_body, obj);
        if (!body) return -1;
        char *p;
        Py_ssize_t n;
        if (PyBytes_AsStringAndSize(body, &p, &n) < 0) {
            Py_DECREF(body);
            return -1;
        }
        int rc = w_raw(w, p, n);
        Py_DECREF(body);
        return rc;
    }
    Py_ssize_t nf = PyTuple_GET_SIZE(fields);
    /* wire-optional trailing fields (Message._optional): a trailing
     * None run is omitted entirely, matching the Python reference walk
     * — the untraced RPC frame stays byte-identical to the schema
     * before the field existed. */
    PyObject *optobj = g_optional_by_id
        ? PyDict_GetItemWithError(g_optional_by_id, idobj) : NULL;
    if (!optobj && PyErr_Occurred()) return -1;
    long long nopt = 0;
    if (optobj) {
        nopt = PyLong_AsLongLong(optobj);
        if (nopt < 0 && PyErr_Occurred()) return -1;
    }
    while (nopt > 0 && nf > 0) {
        PyObject *tail = PyObject_GetAttr(obj, PyTuple_GET_ITEM(fields,
                                                                nf - 1));
        if (!tail) return -1;
        int is_none = (tail == Py_None);
        Py_DECREF(tail);
        if (!is_none) break;
        nf--;
        nopt--;
    }
    for (Py_ssize_t i = 0; i < nf; i++) {
        PyObject *val = PyObject_GetAttr(obj, PyTuple_GET_ITEM(fields, i));
        if (!val) return -1;
        int rc = enc(val, w, depth);
        Py_DECREF(val);
        if (rc < 0) return -1;
    }
    return 0;
}

static int enc(PyObject *obj, Writer *w, int depth) {
    if (++depth > MAX_DEPTH) {
        PyErr_SetString(g_fallback, "graph too deep for the C walker");
        return -1;
    }
    if (obj == Py_None) return w_varint(w, T_NULL);
    if (obj == Py_True) return w_varint(w, T_TRUE);
    if (obj == Py_False) return w_varint(w, T_FALSE);
    if (PyLong_Check(obj)) {
        int overflow = 0;
        long long v = PyLong_AsLongLongAndOverflow(obj, &overflow);
        if (overflow) {
            PyErr_SetString(g_fallback, "int exceeds 64 bits");
            return -1;
        }
        if (v == -1 && PyErr_Occurred()) return -1;
        if (w_varint(w, T_INT) < 0) return -1;
        return w_varint(w, v);
    }
    if (PyFloat_Check(obj)) {
        if (w_varint(w, T_FLOAT) < 0) return -1;
        return w_f64(w, PyFloat_AS_DOUBLE(obj));
    }
    if (PyUnicode_Check(obj)) {
        Py_ssize_t n;
        const char *s = PyUnicode_AsUTF8AndSize(obj, &n);
        if (!s) return -1;
        if (w_varint(w, T_STR) < 0 || w_varint(w, n) < 0) return -1;
        return w_raw(w, s, n);
    }
    if (PyBytes_Check(obj) || PyByteArray_Check(obj)) {
        char *p;
        Py_ssize_t n;
        if (PyBytes_Check(obj)) {
            if (PyBytes_AsStringAndSize(obj, &p, &n) < 0) return -1;
        } else {
            p = PyByteArray_AS_STRING(obj);
            n = PyByteArray_GET_SIZE(obj);
        }
        if (w_varint(w, T_BYTES) < 0 || w_varint(w, n) < 0) return -1;
        return w_raw(w, p, n);
    }
    if (PyList_Check(obj)) {
        if (w_varint(w, T_LIST) < 0 ||
            w_varint(w, PyList_GET_SIZE(obj)) < 0)
            return -1;
        return enc_seq_items(obj, w, depth);
    }
    if (PyTuple_Check(obj)) {
        if (w_varint(w, T_TUPLE) < 0 ||
            w_varint(w, PyTuple_GET_SIZE(obj)) < 0)
            return -1;
        return enc_seq_items(obj, w, depth);
    }
    if (PyAnySet_Check(obj)) {
        /* Python sorts each item's FULL encoding for determinism */
        Py_ssize_t n = PySet_GET_SIZE(obj);
        if (w_varint(w, T_SET) < 0 || w_varint(w, n) < 0) return -1;
        PyObject *parts = PyList_New(0);
        if (!parts) return -1;
        PyObject *it = PyObject_GetIter(obj), *item;
        if (!it) { Py_DECREF(parts); return -1; }
        while ((item = PyIter_Next(it)) != NULL) {
            Writer iw = {NULL, 0, 0};
            if (enc(item, &iw, depth) < 0) {
                Py_DECREF(item); Py_DECREF(it); Py_DECREF(parts);
                PyMem_Free(iw.buf);
                return -1;
            }
            Py_DECREF(item);
            PyObject *bs = PyBytes_FromStringAndSize((char *)iw.buf, iw.len);
            PyMem_Free(iw.buf);
            if (!bs || PyList_Append(parts, bs) < 0) {
                Py_XDECREF(bs); Py_DECREF(it); Py_DECREF(parts);
                return -1;
            }
            Py_DECREF(bs);
        }
        Py_DECREF(it);
        if (PyErr_Occurred()) { Py_DECREF(parts); return -1; }
        if (PyList_Sort(parts) < 0) { Py_DECREF(parts); return -1; }
        for (Py_ssize_t i = 0; i < PyList_GET_SIZE(parts); i++) {
            PyObject *bs = PyList_GET_ITEM(parts, i);
            if (w_raw(w, PyBytes_AS_STRING(bs), PyBytes_GET_SIZE(bs)) < 0) {
                Py_DECREF(parts);
                return -1;
            }
        }
        Py_DECREF(parts);
        return 0;
    }
    if (PyDict_Check(obj)) {
        if (w_varint(w, T_DICT) < 0 ||
            w_varint(w, PyDict_GET_SIZE(obj)) < 0)
            return -1;
        Py_ssize_t pos = 0;
        PyObject *k, *v;
        while (PyDict_Next(obj, &pos, &k, &v)) {
            if (enc(k, w, depth) < 0 || enc(v, w, depth) < 0) return -1;
        }
        return 0;
    }
    if (PyType_Check(obj)) {
        PyObject *idobj = PyDict_GetItemWithError(g_id_by_type, obj);
        if (!idobj) {
            if (!PyErr_Occurred())
                PyErr_Format(g_fallback, "unregistered class %s",
                             ((PyTypeObject *)obj)->tp_name);
            return -1;
        }
        long long tid = PyLong_AsLongLong(idobj);
        if (tid < 0 && PyErr_Occurred()) return -1;
        if (w_varint(w, T_CLASS) < 0) return -1;
        return w_varint(w, tid);
    }
    return enc_registered(obj, w, depth);
}

/* ------------------------------------------------------------------ */
/* decode                                                              */

static PyObject *dec(Reader *r, int depth);

static PyObject *dec_registered(Reader *r, long long tid, int depth) {
    PyObject *idobj = PyLong_FromLongLong(tid);
    if (!idobj) return NULL;
    PyObject *cls = PyDict_GetItemWithError(g_type_by_id, idobj);
    if (!cls) {
        if (!PyErr_Occurred())
            PyErr_Format(g_fallback, "unknown serialization id %lld", tid);
        Py_DECREF(idobj);
        return NULL;
    }
    PyObject *fields = PyDict_GetItemWithError(g_fields_by_id, idobj);
    if (!fields && PyErr_Occurred()) { Py_DECREF(idobj); return NULL; }
    PyObject *optobj = (fields && g_optional_by_id)
        ? PyDict_GetItemWithError(g_optional_by_id, idobj) : NULL;
    Py_DECREF(idobj);
    if (!optobj && PyErr_Occurred()) return NULL;
    if (!fields) {
        PyErr_Format(g_fallback, "no codec meta for id %lld", tid);
        return NULL;
    }
    if (fields == Py_None) { /* custom read_object via Python */
        PyObject *res = PyObject_CallFunction(
            g_decode_body, "OOn", cls, r->source, r->pos);
        if (!res) return NULL;
        PyObject *obj = PyTuple_GetItem(res, 0);
        PyObject *np = PyTuple_GetItem(res, 1);
        if (!obj || !np) { Py_DECREF(res); return NULL; }
        long long newpos = PyLong_AsLongLong(np);
        if (newpos < 0 && PyErr_Occurred()) { Py_DECREF(res); return NULL; }
        r->pos = (Py_ssize_t)newpos;
        Py_INCREF(obj);
        Py_DECREF(res);
        return obj;
    }
    /* Allocate without running __init__ (the generic field-list read
     * path, like serializer.py read_object). tp_new with empty args is
     * exactly what cls.__new__(cls) resolves to for these plain classes
     * — calling the slot directly skips the per-object attribute lookup
     * and bound-staticmethod allocation (measured on 1k-op batch
     * decodes). Classes overriding __new__ still go through their slot. */
    PyObject *obj;
    newfunc tp_new = ((PyTypeObject *)cls)->tp_new;
    if (tp_new) {
        obj = tp_new((PyTypeObject *)cls, g_empty_args, NULL);
    } else {
        PyObject *newf = PyObject_GetAttrString(cls, "__new__");
        if (!newf) return NULL;
        obj = PyObject_CallOneArg(newf, cls);
        Py_DECREF(newf);
    }
    if (!obj) return NULL;
    Py_ssize_t nf = PyTuple_GET_SIZE(fields);
    long long nopt = 0;
    if (optobj) {
        nopt = PyLong_AsLongLong(optobj);
        if (nopt < 0 && PyErr_Occurred()) { Py_DECREF(obj); return NULL; }
    }
    Py_ssize_t required = nf - (Py_ssize_t)nopt;
    for (Py_ssize_t i = 0; i < nf; i++) {
        PyObject *val;
        if (i >= required && r->pos >= r->len) {
            /* omitted wire-optional tail: the message ends its buffer
             * (frames carry exactly one message), fill with None —
             * mirrors Message.read_object in the Python reference */
            val = Py_None;
            Py_INCREF(val);
        } else {
            val = dec(r, depth);
            if (!val) { Py_DECREF(obj); return NULL; }
        }
        int rc = PyObject_SetAttr(obj, PyTuple_GET_ITEM(fields, i), val);
        Py_DECREF(val);
        if (rc < 0) { Py_DECREF(obj); return NULL; }
    }
    return obj;
}

static PyObject *dec(Reader *r, int depth) {
    if (++depth > MAX_DEPTH) {
        PyErr_SetString(g_fallback, "wire graph too deep for the C walker");
        return NULL;
    }
    long long tag;
    if (r_varint(r, &tag) < 0) return NULL;
    switch (tag) {
    case T_NULL: Py_RETURN_NONE;
    case T_TRUE: Py_RETURN_TRUE;
    case T_FALSE: Py_RETURN_FALSE;
    case T_INT: {
        long long v;
        if (r_varint(r, &v) < 0) return NULL;
        return PyLong_FromLongLong(v);
    }
    case T_FLOAT: {
        double d;
        if (r_f64(r, &d) < 0) return NULL;
        return PyFloat_FromDouble(d);
    }
    case T_STR: {
        long long n;
        if (r_varint(r, &n) < 0) return NULL;
        if (n < 0 || r_need(r, (Py_ssize_t)n) < 0) return NULL;
        PyObject *s = PyUnicode_DecodeUTF8(
            (const char *)r->data + r->pos, (Py_ssize_t)n, NULL);
        if (s) r->pos += (Py_ssize_t)n;
        return s;
    }
    case T_BYTES: {
        long long n;
        if (r_varint(r, &n) < 0) return NULL;
        if (n < 0 || r_need(r, (Py_ssize_t)n) < 0) return NULL;
        PyObject *b = PyBytes_FromStringAndSize(
            (const char *)r->data + r->pos, (Py_ssize_t)n);
        if (b) r->pos += (Py_ssize_t)n;
        return b;
    }
    case T_LIST: {
        long long n;
        if (r_varint(r, &n) < 0 || n < 0) return NULL;
        PyObject *lst = PyList_New((Py_ssize_t)n);
        if (!lst) return NULL;
        for (Py_ssize_t i = 0; i < (Py_ssize_t)n; i++) {
            PyObject *item = dec(r, depth);
            if (!item) { Py_DECREF(lst); return NULL; }
            PyList_SET_ITEM(lst, i, item);
        }
        return lst;
    }
    case T_TUPLE: {
        long long n;
        if (r_varint(r, &n) < 0 || n < 0) return NULL;
        PyObject *tup = PyTuple_New((Py_ssize_t)n);
        if (!tup) return NULL;
        for (Py_ssize_t i = 0; i < (Py_ssize_t)n; i++) {
            PyObject *item = dec(r, depth);
            if (!item) { Py_DECREF(tup); return NULL; }
            PyTuple_SET_ITEM(tup, i, item);
        }
        return tup;
    }
    case T_SET: {
        long long n;
        if (r_varint(r, &n) < 0 || n < 0) return NULL;
        PyObject *set = PySet_New(NULL);
        if (!set) return NULL;
        for (Py_ssize_t i = 0; i < (Py_ssize_t)n; i++) {
            PyObject *item = dec(r, depth);
            if (!item || PySet_Add(set, item) < 0) {
                Py_XDECREF(item); Py_DECREF(set);
                return NULL;
            }
            Py_DECREF(item);
        }
        return set;
    }
    case T_DICT: {
        long long n;
        if (r_varint(r, &n) < 0 || n < 0) return NULL;
        PyObject *d = PyDict_New();
        if (!d) return NULL;
        for (Py_ssize_t i = 0; i < (Py_ssize_t)n; i++) {
            PyObject *k = dec(r, depth); /* key first, like the dict comp */
            if (!k) { Py_DECREF(d); return NULL; }
            PyObject *v = dec(r, depth);
            if (!v || PyDict_SetItem(d, k, v) < 0) {
                Py_DECREF(k); Py_XDECREF(v); Py_DECREF(d);
                return NULL;
            }
            Py_DECREF(k);
            Py_DECREF(v);
        }
        return d;
    }
    case T_CLASS: {
        long long tid;
        if (r_varint(r, &tid) < 0) return NULL;
        PyObject *idobj = PyLong_FromLongLong(tid);
        if (!idobj) return NULL;
        PyObject *cls = PyDict_GetItemWithError(g_type_by_id, idobj);
        Py_DECREF(idobj);
        if (!cls) {
            if (!PyErr_Occurred())
                PyErr_Format(g_fallback, "unknown class id %lld", tid);
            return NULL;
        }
        Py_INCREF(cls);
        return cls;
    }
    default:
        if (tag < 16) {
            PyErr_Format(g_fallback, "unknown wire tag %lld", tag);
            return NULL;
        }
        return dec_registered(r, tag - 16, depth);
    }
}

/* ------------------------------------------------------------------ */
/* module functions                                                    */

static PyObject *codec_encode(PyObject *self, PyObject *obj) {
    (void)self;
    Writer w = {NULL, 0, 0};
    if (enc(obj, &w, 0) < 0) {
        PyMem_Free(w.buf);
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize((char *)w.buf, w.len);
    PyMem_Free(w.buf);
    return out;
}

static PyObject *codec_decode(PyObject *self, PyObject *data) {
    (void)self;
    if (!PyBytes_Check(data)) {
        PyErr_SetString(PyExc_TypeError, "decode() needs bytes");
        return NULL;
    }
    Reader r = {(const unsigned char *)PyBytes_AS_STRING(data),
                PyBytes_GET_SIZE(data), 0, data};
    PyObject *obj = dec(&r, 0);
    if (obj && r.pos != r.len) {
        /* trailing bytes mean a framing mismatch — surface it */
        Py_DECREF(obj);
        PyErr_Format(g_fallback, "decode left %zd trailing bytes",
                     r.len - r.pos);
        return NULL;
    }
    return obj;
}

/* ------------------------------------------------------------------ */
/* frame-burst walk: [u32 len][u8 kind][u64 corr][payload]...           */
/* The shared TCP wire framing (io/tcp.py _HEADER = ">IBQ") walked in   */
/* one call per read burst: the transports hand whole read buffers to   */
/* decode_frames and whole response bursts to encode_frames, so the    */
/* session frame walk — batch envelope in, per-op decode, response     */
/* re-encode — stays in C for the full request/response cycle.         */

#define FRAME_HEADER 13

static PyObject *codec_decode_frames(PyObject *self, PyObject *data) {
    (void)self;
    /* buffer protocol, not PyBytes: the TCP read loop accumulates into
       a bytearray (amortized O(n) appends); every decoded object copies
       out of the buffer, so nothing references it after the call */
    Py_buffer view;
    if (PyObject_GetBuffer(data, &view, PyBUF_SIMPLE) != 0) {
        return NULL;
    }
    const unsigned char *buf = (const unsigned char *)view.buf;
    Py_ssize_t total = view.len;
    PyObject *out = PyList_New(0);
    if (!out) { PyBuffer_Release(&view); return NULL; }
    Py_ssize_t pos = 0;
    while (pos + FRAME_HEADER <= total) {
        unsigned long long length = 0, corr = 0;
        for (int i = 0; i < 4; i++) length = (length << 8) | buf[pos + i];
        unsigned char kind = buf[pos + 4];
        for (int i = 0; i < 8; i++) corr = (corr << 8) | buf[pos + 5 + i];
        if (pos + FRAME_HEADER + (Py_ssize_t)length > total) break;
        Reader r = {buf, pos + FRAME_HEADER + (Py_ssize_t)length,
                    pos + FRAME_HEADER, data};
        PyObject *obj = dec(&r, 0);
        if (!obj) { /* incl. Fallback: the caller re-walks this burst
                       frame-by-frame in Python */
            Py_DECREF(out); PyBuffer_Release(&view); return NULL;
        }
        if (r.pos != r.len) {
            Py_DECREF(obj); Py_DECREF(out);
            PyErr_Format(g_fallback, "frame decode left %zd trailing bytes",
                         r.len - r.pos);
            PyBuffer_Release(&view);
            return NULL;
        }
        PyObject *rec = Py_BuildValue("(iKN)", (int)kind, corr, obj);
        if (!rec || PyList_Append(out, rec) < 0) {
            Py_XDECREF(rec); Py_DECREF(out);
            PyBuffer_Release(&view);
            return NULL;
        }
        Py_DECREF(rec);
        pos += FRAME_HEADER + (Py_ssize_t)length;
    }
    PyBuffer_Release(&view);
    return Py_BuildValue("(Nn)", out, pos);
}

static PyObject *codec_encode_frames(PyObject *self, PyObject *frames) {
    (void)self;
    PyObject *fast = PySequence_Fast(frames,
                                     "encode_frames() needs a sequence");
    if (!fast) return NULL;
    Writer w = {NULL, 0, 0};
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PySequence_Fast_GET_ITEM(fast, i);
        int kind;
        unsigned long long corr;
        PyObject *obj;
        if (!PyArg_ParseTuple(item, "iKO", &kind, &corr, &obj)) {
            Py_DECREF(fast); PyMem_Free(w.buf);
            return NULL;
        }
        Py_ssize_t hdr = w.len;
        if (w_reserve(&w, FRAME_HEADER) < 0) {
            Py_DECREF(fast); PyMem_Free(w.buf);
            return NULL;
        }
        w.len += FRAME_HEADER;
        if (enc(obj, &w, 0) < 0) {
            Py_DECREF(fast); PyMem_Free(w.buf);
            return NULL;
        }
        unsigned long long length = (unsigned long long)(w.len - hdr
                                                         - FRAME_HEADER);
        for (int b = 0; b < 4; b++)
            w.buf[hdr + b] = (unsigned char)(length >> (24 - 8 * b));
        w.buf[hdr + 4] = (unsigned char)kind;
        for (int b = 0; b < 8; b++)
            w.buf[hdr + 5 + b] = (unsigned char)(corr >> (56 - 8 * b));
    }
    Py_DECREF(fast);
    PyObject *out = PyBytes_FromStringAndSize((char *)w.buf, w.len);
    PyMem_Free(w.buf);
    return out;
}

static PyObject *codec_configure(PyObject *self, PyObject *args) {
    (void)self;
    PyObject *ibt, *tbi, *fbi, *eb, *db, *obi = NULL;
    if (!PyArg_ParseTuple(args, "OOOOO|O", &ibt, &tbi, &fbi, &eb, &db,
                          &obi))
        return NULL;
    Py_XDECREF(g_id_by_type); Py_INCREF(ibt); g_id_by_type = ibt;
    Py_XDECREF(g_type_by_id); Py_INCREF(tbi); g_type_by_id = tbi;
    Py_XDECREF(g_fields_by_id); Py_INCREF(fbi); g_fields_by_id = fbi;
    Py_XDECREF(g_encode_body); Py_INCREF(eb); g_encode_body = eb;
    Py_XDECREF(g_decode_body); Py_INCREF(db); g_decode_body = db;
    Py_XDECREF(g_optional_by_id); Py_XINCREF(obi); g_optional_by_id = obi;
    Py_RETURN_NONE;
}

static PyMethodDef codec_methods[] = {
    {"configure", codec_configure, METH_VARARGS,
     "configure(id_by_type, type_by_id, fields_by_id, encode_body, "
     "decode_body[, optional_by_id]) — bind the live registries + "
     "fallback hooks."},
    {"encode", codec_encode, METH_O, "encode(obj) -> bytes"},
    {"decode", codec_decode, METH_O, "decode(bytes) -> obj"},
    {"decode_frames", codec_decode_frames, METH_O,
     "decode_frames(bytes) -> ([(kind, corr, obj), ...], consumed) — walk "
     "complete [u32 len][u8 kind][u64 corr][payload] frames in one call."},
    {"encode_frames", codec_encode_frames, METH_O,
     "encode_frames([(kind, corr, obj), ...]) -> bytes — one framed "
     "buffer for a whole response burst."},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef codec_module = {
    PyModuleDef_HEAD_INIT, "copycat_codec",
    "Native Catalyst-wire codec (see io/serializer.py for the format).",
    -1, codec_methods, NULL, NULL, NULL, NULL};

PyMODINIT_FUNC PyInit_copycat_codec(void) {
    PyObject *m = PyModule_Create(&codec_module);
    if (!m) return NULL;
    g_empty_args = PyTuple_New(0);
    if (!g_empty_args) {
        Py_DECREF(m);
        return NULL;
    }
    g_fallback = PyErr_NewException("copycat_codec.Fallback", NULL, NULL);
    if (!g_fallback || PyModule_AddObject(m, "Fallback", g_fallback) < 0) {
        Py_XDECREF(g_fallback);
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(g_fallback); /* module owns one ref; we keep the global */
    return m;
}
